"""Low-latency All-to-All + MoE EP dispatch/combine (analog of reference
python/triton_dist/kernels/nvidia/low_latency_all_to_all.py — the README
showcase kernel, 137 µs vs DeepEP's 182 µs — and ep_a2a.py).

Reference protocol (low_latency_all_to_all.py:35-118): one CTA per peer does
``putmem_nbi_block`` of capacity-padded token data + splits into the peer's
symmetric buffer, ``fence``, ``signal_op``; then ``signal_wait_until`` on its
own flags; double-buffered by call-count parity (:125-164).

TPU-native redesign:

- The token-routing scatter the reference does with warp-level atomic slot
  allocation inside the kernel (ep_a2a.py:64-147) has no TPU analog (no
  per-warp atomics); it is a *static-shape scatter* here, computed on the VPU
  with one-hot cumsums (`route_tokens`) — compiler-friendly and fully
  vectorized.
- The wire collective is ``all_to_all_push``: every PE owns a
  ``[n, capacity, ...]`` payload, slot p goes to peer p; delivery is signaled
  by the receive DMA semaphore (no separate flag word needed). Payload sizes
  are static (capacity-padded) — the reference pads to MAX_M the same way
  (:141-147).
- Per-call output buffers + an entry barrier replace the call-count parity
  scheme: a peer cannot write into a buffer instance of call k+1 before
  every PE has entered call k+1.
"""

from __future__ import annotations

import dataclasses
import typing

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret, on_cpu


def _xla_wire(ctx: ShmemContext, axis: str) -> bool:
    """True when this axis' wire exchange must run as plain XLA collectives
    instead of the Pallas remote-DMA kernel: the host-driven DCN tier
    (remote DMA cannot cross a slice boundary), or the CPU simulator on jax
    builds whose interpreter has no cross-device semaphore/DMA model (the
    0.4.x line — ``get_barrier_semaphore`` and remote copies only lower on
    Mosaic there). ``TDT_FORCE_COMPILED=1`` still traces the kernel path
    for the AOT topology gate."""
    import os
    if ctx.is_dcn_axis(axis):
        return True
    if os.environ.get("TDT_FORCE_COMPILED") == "1":
        return False
    return on_cpu() and not _interp_supports_remote_dma()


def _interp_supports_remote_dma() -> bool:
    """Whether Pallas interpret mode on this jax can execute the remote-DMA
    collective kernel (TPU interpret mode with shared-memory simulation).
    The 0.4.x generic interpreter cannot — it has no lowering for
    ``get_barrier_semaphore`` / cross-device ``make_async_remote_copy``."""
    return (getattr(pltpu, "InterpretParams", None) is not None
            or getattr(pltpu, "TPUInterpretParams", None) is not None)


# ---------------------------------------------------------------------------
# wire collective
# ---------------------------------------------------------------------------

def _quant_slot_pipeline(x_at_p, q_at_p, s_at_p, wire_q, cap, H):
    """Quantize one destination slot's [cap, H] rows into the wire staging
    refs, (128, H) row tiles at a time — the send-edge mirror of
    ``_dequant_slot_pipeline``. Row math is bit-identical to ``_quant``
    (same f32 amax / divide chain; zero rows quantize to zeros with scale
    1). Module-level so the single-device golden test can drive the exact
    kernel tile math without the collective around it."""
    qmax = _qmax(wire_q)
    is_float = jnp.issubdtype(wire_q, jnp.floating)

    def body(x_blk, q_blk, s_blk):
        xf = x_blk[...].astype(jnp.float32)
        amax = jnp.max(jnp.abs(xf), axis=-1)              # [128]
        scale = jnp.where(amax > 0, amax / qmax, 1.0)
        q = xf / scale[:, None]
        if not is_float:
            q = jnp.round(q)
        q_blk[...] = q.astype(wire_q)
        # scale run [i*128, (i+1)*128) of the flattened wire is row i
        # of the [cap//128, 128] side-channel (same layout the dequant
        # pipeline reads back on the receive edge)
        s_blk[...] = scale.reshape(1, -1)

    # whole-(128, H) row tiles: the per-row amax needs the full row in
    # one block, which is why the fused path requires H lane-aligned
    pltpu.emit_pipeline(
        body,
        grid=(cap // 128,),
        in_specs=[pl.BlockSpec((128, H), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((128, H), lambda i: (i, 0)),
                   pl.BlockSpec((1, 128), lambda i: (i, 0))],
    )(x_at_p, q_at_p, s_at_p)


def _dequant_slot_pipeline(q_at_p, s_at_p, o_at_p, out_dtype, cap, H, bn):
    """Dequantize one arrived slot's [cap, H] wire rows into ``o_at_p``,
    (128, bn) tiles at a time (receive edge of the quantized wire)."""

    def body(q_blk, sc_blk, o_blk):
        sc = sc_blk[0]                                    # [128] lanes
        o_blk[...] = (q_blk[...].astype(jnp.float32)
                      * sc[:, None]).astype(out_dtype)

    pltpu.emit_pipeline(
        body,
        grid=(cap // 128, H // bn),
        in_specs=[
            pl.BlockSpec((128, bn), lambda i, j: (i, j)),
            # scale run [i*128, (i+1)*128) of the flattened wire is
            # exactly row i of the [rows, 128] side-channel (the fused
            # path requires cap % 128 == 0 — Mosaic rejects sub-128
            # lane slices)
            pl.BlockSpec((1, 128), lambda i, j: (i, 0)),
        ],
        out_specs=[pl.BlockSpec((128, bn), lambda i, j: (i, j))],
    )(q_at_p, s_at_p, o_at_p)


def _a2a_kernel(axis, mesh_axes, n_arrays, dequant, quant, refs):
    """refs = [in_0..in_{A-1}, (qsend, qsc,)? (deq_out,)?
    out_0..out_{W-1}, send_sems, recv_sems] with W = A wire arrays (A+1
    under ``quant``: the f32 scale wire is appended LAST). Each array is
    [n, ...]: in slot p is the payload for peer p; out slot p is the
    payload received from peer p.

    ``dequant`` (None or ``(out_dtype, cap, H, bn)``; cap % 128 == 0) fuses
    the receive-edge dequantization INTO the collective: array 0 is then the
    quantized [n, cap, H] payload, the LAST array its f32 scale wire
    [n, cap_cols//128, 128], and each peer's slot is dequantized into
    ``deq_out`` as soon as it arrives — early arrivals' dequant overlaps the
    wait for later peers, so only the LAST slot's dequant rides the critical
    path (vs a full extra pass after the kernel). The reference's fp8 wire
    does the same: scales ride the kernel and apply in place
    (low_latency_all_to_all.py:60-88).

    ``quant`` (None or ``(wire_dtype, cap, H)``; cap % 128 == 0) is the
    SEND-side mirror: in_0 is a [n, cap, H] compute-dtype payload that is
    quantized per-row into the ``qsend``/``qsc`` staging buffers — slot p
    tile-by-tile, IMMEDIATELY before slot p's put is issued — so peer p's
    wire bytes leave as soon as its slot is quantized instead of after a
    whole-buffer pass, and no standalone qpack pass exists outside the
    collective. Row math is bit-identical to ``_quant`` (same f32 amax /
    divide chain, zero rows quantize to zeros with scale 1)."""
    ins = refs[:n_arrays]
    off = n_arrays
    if quant is not None:
        qsend, qsc = refs[off], refs[off + 1]
        off += 2
    deq = None
    if dequant is not None:
        deq = refs[off]
        off += 1
    n_wire = n_arrays + (1 if quant is not None else 0)
    outs = refs[off:off + n_wire]
    send_sems, recv_sems = refs[off + n_wire:]
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)

    # send sources: under quant, the staged wire payload replaces in_0 and
    # the staged scales ride as the extra LAST wire array
    srcs = ((qsend,) + tuple(ins[1:]) + (qsc,)) if quant is not None else ins

    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    def quant_slot(p):
        wire_q, cap, H = quant
        _quant_slot_pipeline(ins[0].at[p], qsend.at[p], qsc.at[p],
                             wire_q, cap, H)

    if quant is not None:
        quant_slot(me)
    local_copies = []
    for a in range(n_wire):
        c = pltpu.make_async_copy(srcs[a].at[me], outs[a].at[me],
                                  recv_sems.at[a, me])
        c.start()
        local_copies.append(c)
    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        if quant is not None:
            quant_slot(dst)   # slot dst's wire bytes exist just in time
        for a in range(n_wire):
            rdmas.append(shd.putmem_nbi(outs[a].at[me], srcs[a].at[dst],
                                        send_sems.at[a, dst],
                                        recv_sems.at[a, me], pid))

    def dequant_slot(p):
        out_dtype, cap, H, bn = dequant
        _dequant_slot_pipeline(outs[0].at[p], outs[-1].at[p], deq.at[p],
                               out_dtype, cap, H, bn)

    for c in local_copies:
        c.wait()
    if dequant is not None:
        dequant_slot(me)
    for p in range(1, n):
        src = lax.rem(me + p, n)
        for a in range(n_arrays):
            shd.wait_recv(outs[a].at[src], recv_sems.at[a, src])
        if dequant is not None:
            dequant_slot(src)
    shd.quiet(*rdmas)


def all_to_all_push(ctx: ShmemContext, *arrays: jax.Array,
                    axis: str | None = None,
                    spec: P | None = None,
                    dequant_to=None,
                    fuse_dequant: bool = True,
                    quant_from=None,
                    fuse_quant: bool = True) -> tuple[jax.Array, ...]:
    """Generic low-latency All-to-All: each input is locally ``[n, ...]``
    where slot p is the payload destined for peer p along ``axis``. Returns
    same-shaped arrays where local slot p holds the payload *received from*
    peer p. One kernel, one put per (peer, array), arrival = DMA semaphore.

    ``spec`` is the dim-0 sharding of the global arrays. The default
    ``P(axis)`` means globally ``[n*n, ...]`` with devices differing only on
    other mesh axes holding replicas (data-parallel semantics). Pass
    ``P(mesh_axes)`` (flat, globally ``[n_devices*n, ...]``) when every
    device holds distinct payloads — e.g. one tier of the hierarchical
    dispatch.

    ``dequant_to=<dtype>`` fuses the receive-edge dequantization into the
    kernel (quantized-wire convention: ``arrays[0]`` is the [n, cap, H]
    payload, ``arrays[-1]`` its per-slot f32 scale wire). The first returned
    array is then [n, cap, H] in ``<dtype>`` — each peer's slot dequantized
    as it arrived, overlapping the waits for later peers.
    ``fuse_dequant=False`` keeps the dequant as one post-kernel XLA pass
    instead (cheaper at n=1 where there are no later-peer waits to hide the
    in-kernel pipeline behind; see docs/benchmarks.md fp8-edge table).

    ``quant_from=<wire dtype>`` is the send-side mirror: ``arrays[0]`` is a
    compute-dtype [n, cap, H] payload that the KERNEL quantizes per
    destination slot, tile-by-tile, immediately before that slot's put —
    no standalone qpack pass precedes the collective, and peer p's bytes
    leave as soon as slot p is quantized. The f32 scale wire is created
    internally and returned as the LAST output (so returns have
    ``len(arrays) + 1`` entries: quantized payload (or its dequantized form
    under ``dequant_to``), pass-through arrays, scale). Sub-128 caps, DCN
    tiers and ``fuse_quant=False`` fall back to one XLA quantize pass in
    front of the plain wire push — same outputs, bit-identical rows."""
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    spec = spec if spec is not None else P(axis)
    n_arrays = len(arrays)
    quant = None
    if quant_from is not None:
        wire_q = jnp.dtype(quant_from)
        cap_q, H_q = arrays[0].shape[-2:]
        q_aligned = cap_q % 128 == 0 and H_q % 128 == 0
        if _xla_wire(ctx, axis) or not (fuse_quant and q_aligned):
            # send-edge fallback (host-driven DCN tier / CPU simulator,
            # sub-128 caps that can't take the in-kernel (128, H) row
            # tiles, or an explicit fuse_quant=False): one XLA quantize
            # pass, then the plain quantized-wire push below
            cols = _id_cols(cap_q)

            def _qpack(x):
                nl = x.shape[0]
                q, s = _quant(x.reshape(nl * cap_q, H_q), wire_q)
                sc = jnp.ones((nl, cols), jnp.float32).at[:, :cap_q].set(
                    s.reshape(nl, cap_q))
                return q.reshape(x.shape), sc.reshape(nl, -1, 128)

            pq, psc = ctx.shard_map(_qpack, in_specs=spec,
                                    out_specs=(spec, spec))(arrays[0])
            return all_to_all_push(ctx, pq, *arrays[1:], psc, axis=axis,
                                   spec=spec, dequant_to=dequant_to,
                                   fuse_dequant=fuse_dequant)
        quant = (wire_q, cap_q, H_q)
    if _xla_wire(ctx, axis):
        # DCN tier (or CPU simulator without a remote-DMA interpreter):
        # remote DMA cannot cross a slice boundary — run this axis'
        # exchange as an XLA ``lax.all_to_all`` (host-driven DCN
        # transfers, XLA-scheduled). Identical slot semantics: local slot
        # p of dim -3 goes to peer p / arrives from peer p. The
        # hierarchical ops compose per-axis pushes, so marking the outer
        # axis DCN re-routes exactly that tier (reference inter-node
        # transport split, allgather.py:291-375).
        def xla_tier(*shards):
            # local view: every wire array is [n, ...] with dim 0 = peer
            # slot; exchanging dim 0 IS the push semantics
            return tuple(
                lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                               tiled=True)
                for s in shards)

        sm = ctx.shard_map(xla_tier, in_specs=tuple(spec for _ in arrays),
                           out_specs=tuple(spec for _ in arrays))
        out = sm(*arrays)
        if dequant_to is not None:
            cap = arrays[0].shape[-2]
            scale = out[-1].reshape(out[-1].shape[0], -1)[:, :cap]
            return (_dequant(out[0], scale, dequant_to),) + out[1:]
        return out
    dequant = None
    cap = None
    if dequant_to is not None:
        import math
        if quant is None:
            assert n_arrays >= 2, "quantized wire needs payload + scale arrays"
        _, cap, H = arrays[0].shape[-3:]
        if fuse_dequant and cap % 128 == 0 and H % 128 == 0:
            # in-kernel per-arrival dequant (sub-128 caps or hidden dims
            # would need unaligned lane slices — gcd(512, H) < 128 makes
            # the (128, bn) BlockSpec lane-unaligned — which Mosaic
            # rejects; those fall back to the post-kernel pass below)
            dequant = (jnp.dtype(dequant_to), cap, H, math.gcd(512, H))

    def f(*shards):
        kernel = lambda *refs: _a2a_kernel(axis, mesh_axes, n_arrays,
                                           dequant, quant, refs)
        n_loc = shards[0].shape[0]
        pre = ()
        if quant is not None:
            q_sds = jax.ShapeDtypeStruct(shards[0].shape, wire_q)
            sc_sds = jax.ShapeDtypeStruct((n_loc, cap_q // 128, 128),
                                          jnp.float32)
            pre = (q_sds, sc_sds)       # send-side staging (wire + scales)
            wire_outs = (q_sds,) + tuple(
                jax.ShapeDtypeStruct(s.shape, s.dtype)
                for s in shards[1:]) + (sc_sds,)
        else:
            wire_outs = tuple(
                jax.ShapeDtypeStruct(s.shape, s.dtype) for s in shards)
        deq_shape = ()
        if dequant is not None:
            deq_shape = (jax.ShapeDtypeStruct(shards[0].shape, dequant[0]),)
        n_wire = len(wire_outs)
        out = pl.pallas_call(
            kernel,
            out_shape=pre + deq_shape + wire_outs,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_arrays,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * (
                len(pre) + len(deq_shape) + n_wire),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((n_wire, n)),
                pltpu.SemaphoreType.DMA((n_wire, n)),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                # keyed by axis: the 2-tier dispatch runs two of these
                # back-to-back over different axes — sharing one physical
                # barrier semaphore would let stage-2 signals satisfy
                # devices still waiting in stage 1 (cf. allgather.py)
                collective_id=collective_id_for(f"all_to_all_{axis}")),
            interpret=default_interpret(),
        )(*shards)
        out = out[len(pre):]            # drop the send-side staging
        if dequant is not None:
            # visible outs = (dequantized, raw wire ws, rest...): swap the
            # raw payload ws for the dequantized buffer, keep the rest
            return (out[0],) + out[2:]
        if dequant_to is not None:
            # unfused fallback (cap not 128-aligned): one XLA pass after
            # the kernel
            scale = out[-1].reshape(out[-1].shape[0], -1)[:, :cap]
            return (_dequant(out[0], scale, dequant_to),) + out[1:]
        return out if isinstance(out, tuple) else (out,)

    n_out = n_arrays + (1 if quant is not None else 0)
    sm = ctx.shard_map(f, in_specs=tuple(spec for _ in arrays),
                       out_specs=tuple(spec for _ in range(n_out)))
    return sm(*arrays)


def _seg_chunks(shape: tuple, segments: int, itemsize: int):
    """Static per-segment ``(row_offset, rows)`` split of one wire array's
    slot rows (dim 1 of the local ``[n, rows, ...]`` view), each boundary
    rounded DOWN to the dtype's sublane tile so every chunk's DMA slice
    meets Mosaic's tiling alignment (same 8/16/32-row tiles as
    ``_cap_round``). Arrays too small (or too low-rank) to split ride whole
    in segment 0 — the ``"full"`` sentinel — so side-channels like the id
    wire gate on the first segment's signal. Degenerate chunks are ``None``
    (no put, no wait)."""
    if len(shape) < 3:
        return ("full",) + (None,) * (segments - 1)
    rows = shape[1]
    align = max(1, 32 // max(1, itemsize))
    bounds = [0]
    for s in range(1, segments):
        b = (rows * s // segments) // align * align
        bounds.append(max(bounds[-1], min(b, rows)))
    bounds.append(rows)
    if bounds[1] == 0 and segments > 1:
        # alignment swallowed the split: don't degrade to an all-in-the-
        # LAST-segment schedule — ship whole under segment 0 instead
        return ("full",) + (None,) * (segments - 1)
    return tuple(
        (bounds[s], bounds[s + 1] - bounds[s])
        if bounds[s + 1] > bounds[s] else None
        for s in range(segments))


def _seg_view(ref, idx, chunk):
    """The ref slice one segment chunk addresses: the whole peer slot for
    the ``"full"`` sentinel, a static-size row window otherwise."""
    if chunk == "full":
        return ref.at[idx]
    off, rows = chunk
    return ref.at[idx, pl.ds(off, rows)]


def _a2a_seg_kernel(axis, mesh_axes, n_arrays, chunks, refs):
    """Segmented counted-signal variant of ``_a2a_kernel`` (plain wire
    arrays only — the quant/dequant edges run as XLA passes outside).

    ``chunks[a]`` is the static per-segment row split of array ``a``
    (``_seg_chunks``). The producer issues the puts of one (peer, segment)
    pair and then ANNOUNCES the segment with one counted
    ``shd.signal_op(+1)`` on the peer's per-segment REGULAR semaphore —
    ``ops/page_migrate.py``'s counted-signal protocol. The consumer gates on
    ``shd.signal_wait_until(seg_sems[s], n-1)`` per segment in FIXED order
    and only then drains that segment's receive DMA semaphores — so a
    caller interleaving compute between segment waits overlaps segment
    s+1's flight time with segment s's compute while consuming arrivals in
    a rank-independent order. Every byte lands in the same slot as the
    unsegmented kernel: outputs are bitwise identical, only the schedule is
    finer."""
    segments = len(chunks[0])
    ins = refs[:n_arrays]
    outs = refs[n_arrays:2 * n_arrays]
    send_sems = refs[2 * n_arrays]
    recv_sems = refs[2 * n_arrays + 1]
    seg_sems = refs[2 * n_arrays + 2:]
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)

    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    local_copies = []
    for a in range(n_arrays):
        c = pltpu.make_async_copy(ins[a].at[me], outs[a].at[me],
                                  recv_sems.at[a, me, 0])
        c.start()
        local_copies.append(c)
    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        for s in range(segments):
            for a in range(n_arrays):
                if chunks[a][s] is None:
                    continue
                rdmas.append(shd.putmem_nbi(
                    _seg_view(outs[a], me, chunks[a][s]),
                    _seg_view(ins[a], dst, chunks[a][s]),
                    send_sems.at[a, dst, s],
                    recv_sems.at[a, me, s], pid))
            # announce segment s the moment its puts are in flight —
            # the peer's gate for starting compute on s while s+1 flies
            shd.signal_op(seg_sems[s], 1, pe=pid)
    for c in local_copies:
        c.wait()
    if n > 1:
        for s in range(segments):
            shd.signal_wait_until(seg_sems[s], n - 1)
            for p in range(1, n):
                src = lax.rem(me + p, n)
                for a in range(n_arrays):
                    if chunks[a][s] is None:
                        continue
                    shd.wait_recv(_seg_view(outs[a], src, chunks[a][s]),
                                  recv_sems.at[a, src, s])
    shd.quiet(*rdmas)


def all_to_all_push_seg(ctx: ShmemContext, *arrays: jax.Array,
                        axis: str | None = None,
                        spec: P | None = None,
                        segments: int = 2,
                        dequant_to=None,
                        fuse_dequant: bool = False,
                        quant_from=None,
                        fuse_quant: bool = False) -> tuple[jax.Array, ...]:
    """Segmented counted-signal variant of ``all_to_all_push`` — the wire
    collective behind the serving overlap schedule (ISSUE 16). Each
    (peer, array) payload is split row-wise into ``segments`` static
    chunks; the producer announces every segment with one counted
    ``signal_op`` after its puts are issued and the consumer drains
    segments in fixed order behind per-segment ``signal_wait_until`` gates
    (``ops/page_migrate.py``'s protocol). The same bytes land in the same
    slots as the plain push — outputs are BITWISE identical; only the
    delivery schedule is finer, which is what lets the microbatched EP
    pipeline overlap expert compute with the next microbatch's flight.

    ``fuse_dequant`` / ``fuse_quant`` are accepted for call-site parity
    with ``all_to_all_push`` and ignored: the segmented wire always takes
    the UNFUSED quant/dequant edges (one XLA pass outside the collective),
    whose rows are bit-identical to the fused in-kernel pipelines by
    construction (same f32 amax/divide chain — see ``_quant_slot_pipeline``).
    DCN tiers and the CPU simulator fall back to ``all_to_all_push``'s XLA
    exchange — identical slot semantics, identical bytes."""
    del fuse_dequant, fuse_quant
    axis = axis or ctx.axis_names[0]
    segments = max(1, int(segments))
    spec = spec if spec is not None else P(axis)
    if quant_from is not None:
        # always the send-edge XLA quantize pass (bit-identical rows to the
        # fused path), then the plain quantized-wire segmented push
        wire_q = jnp.dtype(quant_from)
        cap_q, H_q = arrays[0].shape[-2:]
        cols = _id_cols(cap_q)

        def _qpack(x):
            nl = x.shape[0]
            q, s = _quant(x.reshape(nl * cap_q, H_q), wire_q)
            sc = jnp.ones((nl, cols), jnp.float32).at[:, :cap_q].set(
                s.reshape(nl, cap_q))
            return q.reshape(x.shape), sc.reshape(nl, -1, 128)

        pq, psc = ctx.shard_map(_qpack, in_specs=spec,
                                out_specs=(spec, spec))(arrays[0])
        return all_to_all_push_seg(ctx, pq, *arrays[1:], psc, axis=axis,
                                   spec=spec, segments=segments,
                                   dequant_to=dequant_to)
    if _xla_wire(ctx, axis):
        return all_to_all_push(ctx, *arrays, axis=axis, spec=spec,
                               dequant_to=dequant_to, fuse_dequant=False)
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    n_arrays = len(arrays)
    cap = arrays[0].shape[-2] if dequant_to is not None else None

    def f(*shards):
        chunks = tuple(
            _seg_chunks(s.shape, segments, jnp.dtype(s.dtype).itemsize)
            for s in shards)
        n_segs = len(chunks[0])
        kernel = lambda *refs: _a2a_seg_kernel(axis, mesh_axes, n_arrays,
                                               chunks, refs)
        out = pl.pallas_call(
            kernel,
            out_shape=tuple(jax.ShapeDtypeStruct(s.shape, s.dtype)
                            for s in shards),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * n_arrays,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * n_arrays,
            scratch_shapes=(
                [pltpu.SemaphoreType.DMA((n_arrays, n, n_segs)),
                 pltpu.SemaphoreType.DMA((n_arrays, n, n_segs))]
                + [pltpu.SemaphoreType.REGULAR] * n_segs),
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"all_to_all_seg_{axis}")),
            interpret=default_interpret(),
        )(*shards)
        return out if isinstance(out, tuple) else (out,)

    sm = ctx.shard_map(f, in_specs=tuple(spec for _ in arrays),
                       out_specs=tuple(spec for _ in arrays))
    out = sm(*arrays)
    if dequant_to is not None:
        scale = out[-1].reshape(out[-1].shape[0], -1)[:, :cap]
        return (_dequant(out[0], scale, dequant_to),) + out[1:]
    return out


# ---------------------------------------------------------------------------
# MoE EP dispatch / combine
# ---------------------------------------------------------------------------

class QuantTokens(typing.NamedTuple):
    """Quantized-wire tokens as dispatched: ``q`` [..., cap, H] in the wire
    dtype plus the per-slot f32 ``scale`` [..., cap]. Produced by
    ``dispatch`` under ``dequant_edge="expert"`` — the scales are meant to
    be consumed by the expert grouped GEMM's accumulator
    (``ops.group_gemm.grouped_gemm(row_scale=...)``), never applied in a
    standalone pass; the reference's fp8 protocol works the same way (its
    post_process only slices, low_latency_all_to_all.py:251-270 — scales
    ride into the expert GEMM)."""
    q: jax.Array
    scale: jax.Array

@dataclasses.dataclass(frozen=True)
class EpAllToAllContext:
    """Analog of the reference's A2A context dataclass
    (low_latency_all_to_all.py:125-164): static shapes + mesh info.
    ``capacity`` is the per-(src,dst) token budget — tokens routed beyond it
    are dropped (standard expert-capacity semantics; the reference instead
    sizes buffers for the worst case, which equals
    ``capacity = max_tokens * topk``).

    ``wire_dtype`` (e.g. ``jnp.float8_e4m3fn`` or ``jnp.int8``) enables the
    quantized wire format: tokens ride the A2A as per-token symmetric
    quantized rows plus an f32 scale side-channel payload, halving (vs bf16)
    the wire bytes — the reference's fp8+scales showcase protocol
    (low_latency_all_to_all.py:60-88, README.md:55). Dequantization happens
    at the receiving edge; expert compute stays in ``dtype``.

    The wire-edge strategies (swept on-chip at the DeepSeek-infer
    shape, round 4 — docs/benchmarks.md fp8-edge table):
    - ``quant_edge``: "fused" (default, measured 93.5 µs dispatch) gathers
      rows and quantizes per slot in one fused XLA pass; "pre" (131.9 µs)
      quantizes the T source rows once and gathers the 1-byte wire rows —
      slower on TPU: sub-word row gathers don't vectorize as well as the
      fused f32 gather+quant chain. "kernel" gathers rows in the compute
      dtype and quantizes INSIDE the collective, per destination slot,
      immediately before that slot's put (``all_to_all_push(quant_from=)``)
      — peer p's wire bytes leave as soon as slot p is quantized, the
      multi-chip mirror of the per-arrival dequant.
    - ``dequant_edge``: "post" (default) = one XLA pass after the
      collective; "kernel" = per-arrival in-kernel ``emit_pipeline``
      dequant. Measured +106-125 µs at n=1 — the pipeline's fine-grained
      (128, bn) steps cost far more than the one fused XLA pass, so
      "kernel" is only worth trying multi-chip where it overlaps waits
      for later peers. "expert" skips dequantization entirely:
      ``dispatch`` returns ``QuantTokens(q, scale)`` and the expert
      grouped GEMM folds the scale into its f32 accumulator
      (``grouped_gemm(row_scale=...)``) — no dequant pass anywhere, and
      the expert reads half the token bytes. This is the reference's
      architecture (scales ride into the expert GEMM; its post_process
      never applies them).

    ``expert_major``: lay each (src, dst) capacity block out EXPERT-major —
    slots are grouped per (dst rank, local expert) with a per-expert budget
    ``capacity_per_expert = capacity // experts_per_rank``, so multinomial
    routing spill past one expert's budget is capped AT THE SOURCE instead
    of raggedly padding the receiver's block alignment (the roofline
    attributes ~25 % extra weight traffic to that padding: ≈20-of-16 used
    blocks at the DeepSeek serving shape). Rows
    ``[e*cap_e, (e+1)*cap_e)`` of every received src block belong to local
    expert ``e`` by construction, which makes the consumer's block→expert
    table a static constant and deletes the align gather/scatter passes
    entirely when ``cap_e`` is a block_m multiple
    (``moe_mlp_ep_overlap``). Trade-off: drops are per (src, dst, expert)
    rather than per (src, dst) — heavier skew toward one expert drops
    sooner; size ``capacity`` accordingly."""
    ctx: ShmemContext
    axis: str
    max_tokens: int      # tokens per rank entering dispatch
    hidden: int
    topk: int
    num_experts: int     # global expert count
    capacity: int        # slots per (src,dst) rank pair
    dtype: jnp.dtype = jnp.bfloat16
    wire_dtype: jnp.dtype | None = None
    quant_edge: str = "fused"     # "fused" | "pre" | "kernel"
    dequant_edge: str = "post"    # "post" | "kernel"
    expert_major: bool = False
    # >= 2: the wire collective runs as ``all_to_all_push_seg`` with this
    # many per-peer segments — the counted-signal schedule the serving
    # overlap path rides (ISSUE 16). Same bytes, same slots, bit-identical
    # outputs; 0/1 keeps the plain one-put-per-(peer, array) push.
    seg_push: int = 0

    def _dequant_in_kernel(self) -> bool:
        return self.dequant_edge == "kernel"

    @property
    def n_ranks(self) -> int:
        return self.ctx.axis_size(self.axis)

    @property
    def experts_per_rank(self) -> int:
        return self.num_experts // self.n_ranks

    @property
    def capacity_per_expert(self) -> int:
        assert self.expert_major, "capacity is per-rank unless expert_major"
        return self.capacity // self.experts_per_rank


# --- wire-dtype auto-selection (wire-fit driven) ---------------------------
#
# ``wire_dtype="auto"`` picks bf16 vs fp8 per message size from the same
# wire model bench.py's ``bench_a2a_wire_fit`` emits per dtype:
# ``t = t0 + bytes/BW``. fp8 moves half the payload bytes but pays a fixed
# quant/dequant + f32-scale-wire latency, so small dispatches (latency-
# dominated) keep the bf16 wire and large ones (bandwidth-dominated) take
# the fp8 win. Feed measured fits through ``wire_fit=`` — the
# ``{"bf16": {"t0_us", "gb_per_s"}, "fp8": {...}}`` shape of bench.py's
# ``a2a_wire_fit`` extras. The defaults below encode the ICI egress
# roofline (bench.py ``_ICI_EGRESS_GBS``) with a conservative fp8 latency
# premium (quant + dequant XLA passes + the scale side-channel) and only
# matter until a measured fit artifact is supplied.

_DEFAULT_WIRE_FIT = {
    "bf16": {"t0_us": 5.0, "gb_per_s": 180.0},
    "fp8": {"t0_us": 25.0, "gb_per_s": 180.0},
}


def a2a_wire_bytes(n_ranks: int, max_tokens: int, hidden: int, topk: int,
                   wire_dtype=None) -> int:
    """Dispatch+combine wire bytes for one rank at the drop-proof capacity
    (bench.py ``_wire_bytes`` twin — keep the formulas in sync): payload at
    the wire itemsize plus the int32 id columns, plus the f32 scale
    side-channel when quantized."""
    itemsize = jnp.dtype(wire_dtype or jnp.bfloat16).itemsize
    cap = _cap_round(max_tokens * topk, itemsize)
    idc = _id_cols(cap)
    b = n_ranks * (cap * hidden * itemsize + idc * 4)
    if wire_dtype is not None:
        b += n_ranks * idc * 4
    return 2 * b


def pick_wire_dtype(n_ranks: int, max_tokens: int, hidden: int, topk: int,
                    wire_fit: dict | None = None,
                    fp8_dtype=jnp.float8_e4m3fn):
    """Resolve ``wire_dtype="auto"``: ``None`` (bf16 wire) or ``fp8_dtype``,
    whichever the per-dtype wire fit predicts faster at this message size.
    Ties keep the bf16 wire (no quant pass to maintain)."""
    fit = wire_fit or _DEFAULT_WIRE_FIT

    def t_us(dt, seg):
        b = a2a_wire_bytes(n_ranks, max_tokens, hidden, topk, dt)
        return fit[seg]["t0_us"] + b / (fit[seg]["gb_per_s"] * 1e3)

    return None if t_us(None, "bf16") <= t_us(fp8_dtype, "fp8") else fp8_dtype


def create_all_to_all_context(ctx: ShmemContext, max_tokens: int, hidden: int,
                              topk: int, num_experts: int,
                              capacity: int | None = None,
                              axis: str | None = None,
                              dtype=jnp.bfloat16,
                              wire_dtype=None,
                              wire_fit: dict | None = None,
                              quant_edge: str = "fused",
                              dequant_edge: str = "post",
                              expert_major: bool = False,
                              seg_push: int = 0
                              ) -> EpAllToAllContext:
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    if isinstance(wire_dtype, str):
        assert wire_dtype == "auto", wire_dtype
        wire_dtype = pick_wire_dtype(n, max_tokens, hidden, topk,
                                     wire_fit=wire_fit)
    assert num_experts % n == 0, (num_experts, n)
    assert quant_edge in ("pre", "fused", "kernel"), quant_edge
    assert dequant_edge in ("kernel", "post", "expert"), dequant_edge
    if capacity is None:
        capacity = max_tokens * topk  # worst case: everything to one rank
    wire_itemsize = jnp.dtype(wire_dtype or dtype).itemsize
    capacity = _cap_round(capacity, wire_itemsize)
    if expert_major:
        # split the per-rank budget evenly per local expert, each sublane
        # tile-rounded so every expert segment is independently DMA-aligned
        epr = num_experts // n
        cap_e = _cap_round(-(-capacity // epr), wire_itemsize)
        capacity = cap_e * epr
    assert hidden % 128 == 0, f"hidden={hidden} must be a lane multiple (128)"
    return EpAllToAllContext(ctx=ctx, axis=axis, max_tokens=max_tokens,
                             hidden=hidden, topk=topk,
                             num_experts=num_experts, capacity=capacity,
                             dtype=jnp.dtype(dtype),
                             wire_dtype=(jnp.dtype(wire_dtype)
                                         if wire_dtype is not None else None),
                             quant_edge=quant_edge,
                             dequant_edge=dequant_edge,
                             expert_major=expert_major,
                             seg_push=int(seg_push))


def route_tokens(a2a: EpAllToAllContext, topk_ids: jax.Array):
    """Static-shape routing (replaces the reference's in-kernel atomic slot
    allocation, ep_a2a.py:64-147). ``topk_ids`` is the *local* [T, topk]
    expert assignment. Returns (dest [T,k], slot [T,k], valid [T,k]) where
    ``slot`` is the token's position in the capacity-padded lane to rank
    ``dest``. Pure jnp under jit/shard_map; a host routing table (numpy
    ``topk_ids``) takes the native C++ path (``csrc.a2a_slot_assign`` —
    the registered-host-op analog, csrc registry.cc:32-44) with no device
    round-trip. The twins are cross-tested in test_tools.py.

    Under ``expert_major`` the slot allocation groups by (dest rank, LOCAL
    expert) — the global expert id is the virtual destination over
    ``num_experts`` groups of ``capacity_per_expert`` slots each — and the
    returned slot is ``local_expert * cap_e + rank_in_group``, so each
    (src, dst) block arrives expert-segmented and per-expert spill drops at
    the source (see ``EpAllToAllContext.expert_major``)."""
    import numpy as np
    T, k = topk_ids.shape
    epr = a2a.experts_per_rank
    em = getattr(a2a, "expert_major", False)
    cap_e = a2a.capacity_per_expert if em else None
    if isinstance(topk_ids, np.ndarray) and not isinstance(
            topk_ids, jax.Array):
        from triton_dist_tpu import csrc
        ids32 = topk_ids.astype(np.int32)
        dest = ids32 // epr
        if em:
            # same counter kernel, finer groups: one per global expert
            res = csrc.native_or_none("a2a_slot_assign", ids32.reshape(-1),
                                      a2a.num_experts, cap_e)
            if res is not None:
                r, valid = res
                slot = (ids32.reshape(-1) % epr) * cap_e + r
                return dest, slot.reshape(T, k), valid.reshape(T, k)
        else:
            res = csrc.native_or_none("a2a_slot_assign", dest.reshape(-1),
                                      a2a.n_ranks, a2a.capacity)
            if res is not None:
                slot, valid = res
                return dest, slot.reshape(T, k), valid.reshape(T, k)
    dest = topk_ids // epr                                       # [T,k]
    if em:
        r, valid = _slot_assign(topk_ids.reshape(-1), a2a.num_experts, cap_e)
        slot = (topk_ids.reshape(-1) % epr) * cap_e + r
        return dest, slot.reshape(T, k), valid.reshape(T, k)
    slot, valid = _slot_assign(dest.reshape(-1), a2a.n_ranks, a2a.capacity)
    return dest, slot.reshape(T, k), valid.reshape(T, k)


def _a2a_push_fn(a2a):
    """The wire collective for this context: the plain one-put-per-(peer,
    array) push, or — ``seg_push >= 2`` — the segmented counted-signal push
    the serving overlap schedule rides. Bit-identical outputs either way
    (same bytes, same slots); only the delivery schedule differs."""
    if getattr(a2a, "seg_push", 0) >= 2:
        import functools
        return functools.partial(all_to_all_push_seg, segments=a2a.seg_push)
    return all_to_all_push


def dispatch(a2a: EpAllToAllContext, tokens: jax.Array, topk_ids: jax.Array):
    """EP dispatch (analog of ``fast_all_to_all``,
    low_latency_all_to_all.py:189-248). Global inputs sharded P(axis):
    ``tokens`` [n*T, H], ``topk_ids`` [n*T, topk]. Returns
    (recv_tokens [n, n, capacity, H] P(axis), recv_ids [n, n, capacity]
    P(axis), layout) — receiver slot (src, c) holds a token from rank src
    targeting local expert recv_ids[src, c] (or -1 padding). ``layout`` is
    kept for ``combine``."""
    ctx, axis = a2a.ctx, a2a.axis
    n, cap, H, k = a2a.n_ranks, a2a.capacity, a2a.hidden, a2a.topk
    assert tokens.shape == (n * a2a.max_tokens, H), (
        f"dispatch: tokens {tokens.shape} != "
        f"({n}*{a2a.max_tokens}, {H}) from the a2a context")
    assert topk_ids.shape == (n * a2a.max_tokens, k), (
        f"dispatch: topk_ids {topk_ids.shape} != ({n * a2a.max_tokens}, {k})")

    id_cols = _id_cols(cap)  # lane-aligned ids wire
    wire = a2a.wire_dtype
    # quant_edge="kernel": the gather stays in the compute dtype and the
    # collective quantizes per destination slot just before its put
    kq = wire is not None and a2a.quant_edge == "kernel"

    def build(tok_shard, ids_shard):
        dest, slot, valid = route_tokens(a2a, ids_shard)
        T = tok_shard.shape[0]
        d_f, s_f, v_f = (x.reshape(-1) for x in (dest, slot, valid))
        # over-capacity tokens get an out-of-bounds slot -> dropped by the
        # scatter (never clobbering a valid slot)
        s_drop = jnp.where(v_f, s_f, cap)
        local_eid = (ids_shard % a2a.experts_per_rank).reshape(-1)

        src = _slot_src_map(d_f, s_drop,
                            jnp.arange(T * k, dtype=jnp.int32) // k,
                            n, cap, T)
        if wire is not None and a2a.quant_edge == "pre":
            send_buf, send_sc = _slot_gather_prequant(tok_shard, src, wire,
                                                      n, id_cols, cap)
        elif wire is not None and not kq:
            # fused gather+quant: one logical pass builds wire buf + scales
            send_buf, sc = _slot_gather_quant(tok_shard, src, wire)
            send_sc = jnp.ones((n, id_cols), jnp.float32).at[:, :cap].set(
                sc).reshape(n, -1, 128)
        else:
            send_buf = _slot_gather(tok_shard, src, a2a.dtype)
        send_ids = jnp.full((n, id_cols), -1, jnp.int32).at[
            d_f, s_drop].set(local_eid, mode="drop")
        # wire format: [n, rows, 128] so the per-peer DMA slice is
        # lane-aligned on real TPUs
        outs = (send_buf, send_ids.reshape(n, id_cols // 128, 128))
        if wire is not None and not kq:
            outs += (send_sc,)
        return outs + (dest, slot, valid)

    n_wire = 3 if (wire is not None and not kq) else 2
    sm = ctx.shard_map(build, in_specs=(P(axis), P(axis)),
                       out_specs=(P(axis),) * (n_wire + 3))
    if wire is not None and not kq:
        send_buf, send_ids, send_sc, dest, slot, valid = sm(tokens, topk_ids)
    else:
        send_buf, send_ids, dest, slot, valid = sm(tokens, topk_ids)
    push = _a2a_push_fn(a2a)
    if wire is not None and a2a.dequant_edge == "expert":
        # no dequantization anywhere: tokens stay in the wire dtype and the
        # scales ride alongside for the expert GEMM's accumulator
        if kq:
            recv_q, recv_ids_wire, recv_sc = push(
                ctx, send_buf, send_ids, axis=axis, quant_from=wire)
        else:
            recv_q, recv_ids_wire, recv_sc = push(
                ctx, send_buf, send_ids, send_sc, axis=axis)
        unpack_sc = ctx.shard_map(
            lambda w: w.reshape(n, -1)[:, :cap],
            in_specs=P(axis), out_specs=P(axis))
        recv_tokens = QuantTokens(q=recv_q, scale=unpack_sc(recv_sc))
    elif wire is not None:
        # dequant at the receive edge, per the context's dequant_edge
        # policy: one post-kernel XLA pass (default) or per-arrival
        # in-kernel (multi-chip experiment: overlaps later peers' waits)
        if kq:
            recv_tokens, recv_ids_wire, _ = push(
                ctx, send_buf, send_ids, axis=axis, quant_from=wire,
                dequant_to=a2a.dtype, fuse_dequant=a2a._dequant_in_kernel())
        else:
            recv_tokens, recv_ids_wire, _ = push(
                ctx, send_buf, send_ids, send_sc, axis=axis,
                dequant_to=a2a.dtype, fuse_dequant=a2a._dequant_in_kernel())
    else:
        recv_tokens, recv_ids_wire = push(ctx, send_buf, send_ids,
                                          axis=axis)
    unpack = ctx.shard_map(
        lambda w: w.reshape(n, id_cols)[:, :cap],
        in_specs=P(axis), out_specs=P(axis))
    recv_ids = unpack(recv_ids_wire)
    layout = (dest, slot, valid)
    return recv_tokens, recv_ids, layout


def combine(a2a: EpAllToAllContext, processed: jax.Array, layout,
            topk_weights: jax.Array) -> jax.Array:
    """EP combine (analog of ``kernel_combine_token`` ep_a2a.py:150-241 +
    post-process :251-270): send processed tokens back to their source ranks
    at the same slots, then weighted-sum each token's topk copies.
    ``processed`` is [n*n, capacity, H] sharded P(axis) — local [n, cap, H]
    where slot (src, c) is the processed token for rank src's slot c."""
    ctx, axis = a2a.ctx, a2a.axis
    n, cap, H, k = a2a.n_ranks, a2a.capacity, a2a.hidden, a2a.topk
    wire = a2a.wire_dtype
    push = _a2a_push_fn(a2a)
    if wire is not None:
        # quantize the return trip too (reference sends fp8 both ways) —
        # INSIDE the collective, per departure slot (all_to_all_push's
        # quant_from; sub-128 capacities fall back to one XLA pass there)
        if a2a.dequant_edge == "expert":
            # no full-buffer dequant: the scale is gathered with the token
            # in the combine epilogue and folded into the f32 weighted sum
            back, back_sc = push(ctx, processed, axis=axis,
                                 quant_from=wire)
        else:
            back, _ = push(ctx, processed, axis=axis,
                           quant_from=wire,
                           dequant_to=a2a.dtype,
                           fuse_dequant=a2a._dequant_in_kernel())
            back_sc = None
    else:
        (back,) = push(ctx, processed, axis=axis)
        back_sc = None

    def gather_back(back_shard, dest, slot, valid, w, *sc):
        # back_shard: [n, cap, H] — slot (d, c) = my token processed by rank d
        d_f = dest.reshape(-1)
        s_f = jnp.where(valid, slot, 0).reshape(-1)
        tok = back_shard[d_f, s_f]                                # [T*k, H]
        tok = jnp.where(valid.reshape(-1)[:, None], tok, 0).astype(
            jnp.float32)
        if sc:
            s2d = sc[0].reshape(n, -1)[:, :cap]                   # [n, cap]
            tok = tok * jnp.where(valid.reshape(-1), s2d[d_f, s_f],
                                  1.0)[:, None]
        T = dest.shape[0]
        tok = tok.reshape(T, k, H)
        return jnp.sum(tok * w[..., None].astype(jnp.float32),
                       axis=1).astype(a2a.dtype)

    dest, slot, valid = layout
    n_sc = 1 if back_sc is not None else 0
    sm = ctx.shard_map(gather_back,
                       in_specs=(P(axis),) * (5 + n_sc),
                       out_specs=P(axis))
    return sm(back, dest, slot, valid, topk_weights,
              *((back_sc,) if back_sc is not None else ()))


# ---------------------------------------------------------------------------
# 2-tier hierarchical EP dispatch / combine (multi-axis mesh: DCN x ICI)
# ---------------------------------------------------------------------------

def expected_capacity(n_ranks: int, max_tokens: int, topk: int,
                      headroom: float = 2.0, wire_dtype=None) -> int:
    """Per-(src, dst) slot budget sized to EXPECTED load instead of the
    worst case: balanced routing sends ``max_tokens·topk/n`` rows to each
    peer; ``headroom`` (default 2×) absorbs routing skew, and the result
    is rounded to the wire dtype's sublane tile. The default capacity
    (``max_tokens·topk`` per pair) is drop-proof but pads the wire n×
    beyond the actual bytes at scale — the per-link latency model
    (docs/benchmarks.md) assumes a tuned capacity like this one. Tokens
    routed beyond capacity are dropped (standard expert-capacity
    semantics), so pick ``headroom`` to taste for the workload's skew."""
    cap = max(1, int(max_tokens * topk * headroom / max(n_ranks, 1)))
    itemsize = jnp.dtype(wire_dtype).itemsize if wire_dtype is not None else 2
    # never exceed the drop-proof worst case (at n <= headroom the scaled
    # budget would otherwise pad BEYOND everything-to-one-peer)
    return min(_cap_round(cap, itemsize),
               _cap_round(max_tokens * topk, itemsize))


def _cap_round(cap: int, wire_itemsize: int = 2) -> int:
    """Round a slot capacity up to the wire dtype's sublane tile (8 rows ×
    4 bytes: 8 for f32, 16 for bf16, 32 for fp8/int8) so [capacity, hidden]
    DMA slices meet Mosaic's tiling alignment."""
    mult = 32 // wire_itemsize
    return (cap + mult - 1) // mult * mult


def _slot_src_map(dest_flat, slot_drop, src_rows, n_dst, cap, n_rows):
    """slot -> source-row map: a small int scatter ([n_dst, cap]); unfilled
    slots hold ``n_rows`` (out of range)."""
    return jnp.full((n_dst, cap), n_rows, jnp.int32).at[
        dest_flat, slot_drop].set(src_rows, mode="drop")


# Below this source-row count the slot gather runs as a one-hot matmul on
# the MXU instead of an HBM take-gather. The matmul is EXACT (each one-hot
# row has a single 1.0; 1.0·x in bf16 is x; the f32 accumulation sums one
# nonzero), reads the R source rows once (VMEM-resident) instead of
# streaming ~cap duplicated rows through the gather unit, and unfilled
# slots (src >= R) compare to nothing -> all-zero one-hot row -> zeros, the
# same zero-fill the take path wants. At the DeepSeek dispatch shape
# (R = 128 tokens/rank, cap·n = 1024 slots, H = 7168) the FLOP cost is
# ~1.9 GFLOP ≈ 10 µs on the MXU vs a ~30 µs bandwidth-bound gather — the
# dispatch edge the reference builds outside its timed region
# (test_all_to_all.py:313-329) but we count in ours. Past ~512 source rows
# the R-wide contraction stops paying for itself.
_MXU_GATHER_MAX_ROWS = 512


def _slot_onehot(src, R):
    """[*, R] one-hot of the slot->source-row map (unfilled rows all-zero)."""
    return (src.reshape(-1)[:, None]
            == jnp.arange(R, dtype=src.dtype)[None, :])


def _sanitize_rows(rows):
    """Non-finite containment for the slot gathers: a single Inf/NaN source
    row would poison EVERY slot on the MXU one-hot path (the 0.0·x terms of
    the contraction are NaN), so non-finite values are clamped to the
    dtype's finite range (``jnp.nan_to_num``: NaN→0, ±Inf→±max) BEFORE the
    gather — on both paths, so the MXU and take twins stay bit-comparable.
    Behavior change (documented): a token carrying non-finite activations
    now dispatches as its clamped-finite row instead of corrupting the
    whole dispatch; integer/wire-int rows pass through untouched."""
    if jnp.issubdtype(rows.dtype, jnp.floating):
        return jnp.nan_to_num(rows)
    return rows


def _slot_gather(rows, src, out_dtype):
    """Build a [n_dst, cap, H] send buffer by gathering ``rows`` [R, H]
    through the slot->source-row map ``src`` [n_dst, cap] (value R =
    unfilled -> zeros). Small-R path: gather-by-MXU (see
    ``_MXU_GATHER_MAX_ROWS``). Large-R path: one take-gather instead of
    zero-init + scattering pre-expanded rows — half the HBM traffic on the
    dispatch critical path. Non-finite source rows are clamped first
    (``_sanitize_rows``) so one bad row cannot poison every slot via the
    one-hot contraction."""
    rows = _sanitize_rows(rows)
    R = rows.shape[0]
    out_shape = src.shape + rows.shape[1:]
    if R <= _MXU_GATHER_MAX_ROWS and rows.ndim == 2:
        onehot = _slot_onehot(src, R).astype(rows.dtype)
        return jnp.dot(onehot, rows,
                       preferred_element_type=jnp.float32
                       ).astype(out_dtype).reshape(out_shape)
    filled = (src < R)[..., None]
    take = jnp.take(rows, jnp.minimum(src, R - 1).reshape(-1), axis=0)
    return jnp.where(filled, take.reshape(out_shape), 0).astype(out_dtype)


def _qmax(wire_dtype) -> float:
    if jnp.issubdtype(wire_dtype, jnp.floating):
        return float(jnp.finfo(wire_dtype).max)
    return float(jnp.iinfo(wire_dtype).max)


def _quant(x: jax.Array, wire_dtype) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric quantization: (q rows in ``wire_dtype``,
    f32 scale per row). Zero rows get scale 1 (quantize to zeros)."""
    qmax = _qmax(wire_dtype)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / qmax, 1.0)
    q = xf / scale[..., None]
    if not jnp.issubdtype(wire_dtype, jnp.floating):
        q = jnp.round(q)
    return q.astype(wire_dtype), scale


def _slot_gather_quant(rows, src, wire_dtype):
    """Fused ``_slot_gather`` + ``_quant``: build the [n_dst, cap, H]
    quantized send buffer AND its per-slot f32 scales in ONE logical pass
    over the gathered rows. This is the measured-best send edge (round-4
    on-chip sweep, docs/benchmarks.md fp8-edge table: 93.5 µs dispatch vs
    131.9 µs for the quantize-then-gather "pre" wiring at the
    DeepSeek-infer shape — 1-byte row gathers vectorize worse than the
    fused f32 gather+quant chain despite moving fewer bytes).

    A token routed to k slots has its amax recomputed per slot — identical
    scale each time (bit-for-bit: same reduction over the same row).
    Unfilled slots quantize to zeros with scale 1 (``_quant``'s zero-row
    rule). Non-finite source rows are clamped first (``_sanitize_rows``)."""
    rows = _sanitize_rows(rows)
    R = rows.shape[0]
    H = rows.shape[-1]
    if R <= _MXU_GATHER_MAX_ROWS and rows.ndim == 2:
        # gather-by-MXU (see _MXU_GATHER_MAX_ROWS): the one-hot product IS
        # the gathered f32 rows, and the quant chain fuses onto it
        onehot = _slot_onehot(src, R).astype(rows.dtype)
        take = jnp.dot(onehot, rows, preferred_element_type=jnp.float32)
    else:
        filled = src < R
        take = jnp.take(rows, jnp.minimum(src, R - 1).reshape(-1), axis=0)
        take = take.reshape(src.shape + (H,)).astype(jnp.float32)
        take = jnp.where(filled[..., None], take, 0.0)
    q, scale = _quant(take.reshape(-1, H), wire_dtype)
    return (q.reshape(src.shape + (H,)).astype(wire_dtype),
            scale.reshape(src.shape))


def _slot_gather_prequant(rows, src, wire_dtype, n_dst, cols, cap):
    """``quant_edge="pre"`` send edge: quantize the source ``rows`` ONCE,
    then gather quantized rows + per-row scales through the slot map
    ``src`` [n_dst, cap] — all gathered HBM traffic stays in the wire
    dtype. Moves the fewest bytes but measured behind the fused edge on
    TPU (see ``_slot_gather_quant``); kept selectable as the bit-parity
    twin. Returns (send_buf [n_dst, cap, H] wire, scale wire
    [n_dst, cols//128, 128] f32 with 1.0 in unfilled/pad slots)."""
    rows = _sanitize_rows(rows)
    R = rows.shape[0]
    q, s = _quant(rows, wire_dtype)
    send = _slot_gather(q, src, wire_dtype)
    sc = _slot_gather(s[:, None], src, jnp.float32)[..., 0]
    send_sc = jnp.ones((n_dst, cols), jnp.float32).at[:, :cap].set(
        jnp.where(src < R, sc, 1.0))
    return send, send_sc.reshape(n_dst, -1, 128)


def _dequant(q: jax.Array, scale: jax.Array, out_dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
            ).astype(out_dtype)


def _id_cols(cap: int) -> int:
    """Lane-aligned (128) column count for an int32 id wire of ``cap``."""
    return max((cap + 127) // 128 * 128, 128)


def _slot_assign(dest_flat: jax.Array, n: int, cap: int, valid=None):
    """Exclusive-cumsum slot allocation per destination (the static-shape
    replacement for the reference's per-warp atomic slot counters,
    ep_a2a.py:64-147). Returns (slot, ok) — ``ok`` False for over-capacity
    or already-invalid rows."""
    one_hot = jax.nn.one_hot(jnp.clip(dest_flat, 0, n - 1), n,
                             dtype=jnp.int32)
    if valid is not None:
        one_hot = one_hot * valid[:, None].astype(jnp.int32)
    slots = jnp.cumsum(one_hot, axis=0) - one_hot
    slot = jnp.take_along_axis(
        slots, jnp.clip(dest_flat, 0, n - 1)[:, None], axis=1)[:, 0]
    ok = slot < cap
    if valid is not None:
        ok = ok & valid
    return slot, ok


@dataclasses.dataclass(frozen=True)
class Ep2dAllToAllContext:
    """2-tier EP A2A over a (major, minor) mesh — the TPU shape of the
    reference's hierarchical inter-node dispatch (ep_a2a.py:35-147:
    inter-node token forward, then local scatter by expert). Tier 1 crosses
    the major (slow/DCN) axis once to the target major-row; tier 2 scatters
    along the minor (fast/ICI) axis to the expert's rank. Experts are
    sharded over the flattened (major, minor) rank order."""
    ctx: ShmemContext
    axes: tuple[str, str]      # (major, minor)
    max_tokens: int
    hidden: int
    topk: int
    num_experts: int
    cap1: int                  # tier-1 slots per (src, dst-major-row)
    cap2: int                  # tier-2 slots per (src, dst-minor) pair
    dtype: jnp.dtype = jnp.bfloat16
    # quantized wire (fp8/int8 + f32 per-token scale side-channel): tokens
    # are quantized ONCE at the source and the scales ride both tiers with
    # the same slot maps; dequantization happens only at the edges (expert
    # input, combine output) — no requantization at the intermediate hop.
    # This is the reference's showcase configuration (inter-node fp8 A2A,
    # README.md:55) on the hierarchical path.
    wire_dtype: jnp.dtype | None = None
    quant_edge: str = "fused"     # see EpAllToAllContext
    dequant_edge: str = "post"

    def _dequant_in_kernel(self) -> bool:
        return self.dequant_edge == "kernel"

    @property
    def n_major(self) -> int:
        return self.ctx.axis_size(self.axes[0])

    @property
    def n_minor(self) -> int:
        return self.ctx.axis_size(self.axes[1])

    @property
    def n_ranks(self) -> int:
        return self.n_major * self.n_minor

    @property
    def experts_per_rank(self) -> int:
        return self.num_experts // self.n_ranks


def create_all_to_all_context_2d(ctx: ShmemContext, max_tokens: int,
                                 hidden: int, topk: int, num_experts: int,
                                 axes: tuple[str, str] | None = None,
                                 cap1: int | None = None,
                                 cap2: int | None = None,
                                 dtype=jnp.bfloat16,
                                 wire_dtype=None,
                                 quant_edge: str = "fused",
                                 dequant_edge: str = "post"
                                 ) -> Ep2dAllToAllContext:
    axes = axes or (ctx.axis_names[0], ctx.axis_names[1])
    n = ctx.axis_size(axes[0]) * ctx.axis_size(axes[1])
    assert num_experts % n == 0, (num_experts, n)
    assert quant_edge in ("pre", "fused"), quant_edge
    assert dequant_edge in ("kernel", "post", "expert"), dequant_edge
    assert hidden % 128 == 0, f"hidden={hidden} must be a lane multiple (128)"
    itemsize = jnp.dtype(wire_dtype or dtype).itemsize
    if cap1 is None:
        cap1 = max_tokens * topk
    cap1 = _cap_round(cap1, itemsize)
    if cap2 is None:
        cap2 = ctx.axis_size(axes[0]) * cap1
    cap2 = _cap_round(cap2, itemsize)
    return Ep2dAllToAllContext(ctx=ctx, axes=tuple(axes),
                               max_tokens=max_tokens, hidden=hidden,
                               topk=topk, num_experts=num_experts,
                               cap1=cap1, cap2=cap2, dtype=jnp.dtype(dtype),
                               wire_dtype=(jnp.dtype(wire_dtype)
                                           if wire_dtype is not None
                                           else None),
                               quant_edge=quant_edge,
                               dequant_edge=dequant_edge)


def route_tokens_2d(a2a: Ep2dAllToAllContext, topk_ids: jax.Array):
    """Tier-1 (major-hop) routing plan — the same ``a_dst``/``slot``/``ok``
    that ``dispatch_2d``'s first stage computes (build1), reshaped to the
    ``route_tokens`` [T, topk] convention. The tier-2 plan is
    arrival-dependent (it re-slots whatever tokens land on the intermediate
    device), so it can only be produced by ``dispatch_2d`` itself — it is
    returned there as ``layouts[1]``. Pure jnp; runs under jit/shard_map per
    source shard."""
    T, k = topk_ids.shape
    eid = topk_ids.reshape(-1)
    rank = eid // a2a.experts_per_rank
    a_dst = rank // a2a.n_minor
    slot, ok = _slot_assign(a_dst, a2a.n_major, a2a.cap1)
    return (a_dst.reshape(T, k), slot.reshape(T, k), ok.reshape(T, k))


def dispatch_2d(a2a: Ep2dAllToAllContext, tokens: jax.Array,
                topk_ids: jax.Array):
    """2-tier EP dispatch. Global inputs sharded P((major, minor)):
    ``tokens`` [n*T, H], ``topk_ids`` [n*T, topk] (global expert ids).
    Returns (recv_tokens [n, n_minor, cap2, H] P((major, minor)),
    recv_ids — local expert per slot (or -1), layouts for ``combine_2d``).

    Tier 1 (major/DCN): each token hops once to the device with its target
    major coordinate (same minor coordinate as the source). Tier 2
    (minor/ICI): the intermediate re-slots arrivals by target minor
    coordinate and scatters. The reference's two-kernel structure
    (inter-node putmem forward + local expert scatter, ep_a2a.py:35-147)
    maps to two ``all_to_all_push`` tiers with VPU slot allocation."""
    ctx = a2a.ctx
    major, minor = a2a.axes
    nM, nm = a2a.n_major, a2a.n_minor
    epr = a2a.experts_per_rank
    T, H, k = a2a.max_tokens, a2a.hidden, a2a.topk
    cap1, cap2 = a2a.cap1, a2a.cap2
    c1_cols, c2_cols = _id_cols(cap1), _id_cols(cap2)
    both = P((major, minor))

    wire = a2a.wire_dtype

    def build1(tok_shard, ids_shard):
        eid = ids_shard.reshape(-1)                          # [T*k] global
        rank = eid // epr
        a_dst = rank // nm
        slot, ok = _slot_assign(a_dst, nM, cap1)
        s_drop = jnp.where(ok, slot, cap1)
        src = _slot_src_map(a_dst, s_drop,
                            jnp.arange(T * k, dtype=jnp.int32) // k,
                            nM, cap1, T)
        meta = jnp.full((nM, c1_cols), -1, jnp.int32).at[a_dst, s_drop].set(
            eid, mode="drop")
        outs = ()
        if wire is not None and a2a.quant_edge == "pre":
            # quantize ONCE at the source; the f32 scale side-channel rides
            # the same slot maps through both tiers (no requantization)
            send, send_sc = _slot_gather_prequant(tok_shard, src, wire,
                                                  nM, c1_cols, cap1)
            outs = (send_sc,)
        elif wire is not None:
            send, sc = _slot_gather_quant(tok_shard, src, wire)
            outs = (jnp.ones((nM, c1_cols), jnp.float32).at[:, :cap1].set(
                sc).reshape(nM, -1, 128),)
        else:
            send = _slot_gather(tok_shard, src, a2a.dtype)
        return (send, meta.reshape(nM, c1_cols // 128, 128)) + outs + (
            a_dst, slot, ok)

    nw = 3 if wire is not None else 2
    sm1 = ctx.shard_map(build1, in_specs=(both, both),
                        out_specs=(both,) * (nw + 3))
    *wires1, a_dst, slot1, ok1 = sm1(tokens, topk_ids)
    recv1, meta1r, *sc1r = all_to_all_push(ctx, *wires1, axis=major,
                                           spec=both)

    def build2(r1_shard, m1_shard, *sc_shard):
        meta = m1_shard.reshape(nM, c1_cols)[:, :cap1].reshape(-1)
        valid = meta >= 0
        rank = jnp.where(valid, meta, 0) // epr
        b_dst = rank % nm
        slot, ok = _slot_assign(b_dst, nm, cap2, valid)
        toks = r1_shard.reshape(nM * cap1, H)
        s_drop = jnp.where(ok, slot, cap2)
        R = nM * cap1
        src = _slot_src_map(b_dst, s_drop,
                            jnp.arange(R, dtype=jnp.int32),
                            nm, cap2, R)
        # pass-through re-slot: the payload stays in the wire dtype
        send = _slot_gather(toks, src,
                            wire if wire is not None else a2a.dtype)
        meta2 = jnp.full((nm, c2_cols), -1, jnp.int32).at[b_dst, s_drop].set(
            meta, mode="drop")
        outs = ()
        if wire is not None:
            s1 = sc_shard[0].reshape(nM, c1_cols)[:, :cap1].reshape(-1)
            sc2 = _slot_gather(s1[:, None], src, jnp.float32)[..., 0]
            send_sc = jnp.ones((nm, c2_cols), jnp.float32).at[:, :cap2].set(
                jnp.where(src < R, sc2, 1.0))
            outs = (send_sc.reshape(nm, -1, 128),)
        return (send, meta2.reshape(nm, c2_cols // 128, 128)) + outs + (
            b_dst, slot, ok)

    sm2 = ctx.shard_map(build2, in_specs=(both,) * nw,
                        out_specs=(both,) * (nw + 3))
    *wires2, b_dst, slot2, ok2 = sm2(recv1, meta1r, *sc1r)
    if wire is not None and a2a.dequant_edge == "expert":
        # QuantTokens out: the scale side-channel that rode both tiers is
        # handed to the expert GEMM with the wire-dtype rows
        recv2, meta2r, sc2w = all_to_all_push(ctx, *wires2, axis=minor,
                                              spec=both)
        unpack_sc = ctx.shard_map(
            lambda w: w.reshape(nm, -1)[:, :cap2],
            in_specs=both, out_specs=both)
        recv2 = QuantTokens(q=recv2, scale=unpack_sc(sc2w))
    else:
        recv2, meta2r, *sc2r = all_to_all_push(
            ctx, *wires2, axis=minor, spec=both,
            dequant_to=a2a.dtype if wire is not None else None,
            fuse_dequant=a2a._dequant_in_kernel())

    unpack = ctx.shard_map(
        lambda w: jnp.where(
            w.reshape(nm, c2_cols)[:, :cap2] >= 0,
            w.reshape(nm, c2_cols)[:, :cap2] % epr, -1),
        in_specs=both, out_specs=both)
    recv_ids = unpack(meta2r)
    layouts = ((a_dst, slot1, ok1), (b_dst, slot2, ok2))
    return recv2, recv_ids, layouts


def combine_2d(a2a: Ep2dAllToAllContext, processed: jax.Array, layouts,
               topk_weights: jax.Array) -> jax.Array:
    """Reverse path of ``dispatch_2d``: minor-tier return, intermediate
    re-gather to tier-1 arrival order, major-tier return, topk-weighted sum
    at the source (analog of kernel_combine_token, ep_a2a.py:150-241)."""
    ctx = a2a.ctx
    major, minor = a2a.axes
    nM, nm = a2a.n_major, a2a.n_minor
    T, H, k = a2a.max_tokens, a2a.hidden, a2a.topk
    cap1, cap2 = a2a.cap1, a2a.cap2
    c1_cols, c2_cols = _id_cols(cap1), _id_cols(cap2)
    (a_dst, slot1, ok1), (b_dst, slot2, ok2) = layouts
    both = P((major, minor))
    wire = a2a.wire_dtype

    if wire is not None:
        # quantize the return trip once at the experts — inside the minor
        # collective, per departure slot (all_to_all_push's quant_from;
        # sub-128 capacities fall back to one XLA pass there); scales ride
        # both hops with the payload (reference sends fp8 both ways)
        back2, b2sc = all_to_all_push(ctx, processed, axis=minor, spec=both,
                                      quant_from=wire)
    else:
        (back2,) = all_to_all_push(ctx, processed, axis=minor, spec=both)

    def regroup(b2_shard, bd, s2, ok, *scs):
        idx = jnp.where(ok, s2, 0)
        tok = b2_shard[bd, idx]
        if wire is not None:
            tok = jnp.where(ok[:, None], tok, 0).astype(wire)
            # reshape(nm, -1): the fused-quant scale wire is
            # [nm, cap2//128, 128]; the XLA-fallback wire [nm, c2_cols//128,
            # 128] — both flatten to >= cap2 scale columns
            sv = scs[0].reshape(nm, -1)[:, :cap2][bd, idx]
            sc = jnp.ones((nM, c1_cols), jnp.float32).at[:, :cap1].set(
                jnp.where(ok, sv, 1.0).reshape(nM, cap1))
            return (tok.reshape(nM, cap1, H), sc.reshape(nM, -1, 128))
        tok = jnp.where(ok[:, None], tok, 0).astype(a2a.dtype)
        return (tok.reshape(nM, cap1, H),)

    nmid = 2 if wire is not None else 1
    mid = ctx.shard_map(
        regroup, in_specs=(both,) * (4 + (1 if wire is not None else 0)),
        out_specs=(both,) * nmid)(
        back2, b_dst, slot2, ok2, *((b2sc,) if wire is not None else ()))
    back1, *b1sc = all_to_all_push(ctx, *mid, axis=major, spec=both)

    def gather(b1_shard, ad, s1, ok, w, *scs):
        idx = jnp.where(ok, s1, 0)
        tok = b1_shard[ad, idx]
        tok = jnp.where(ok[:, None], tok, 0)
        if wire is not None:
            sv = scs[0].reshape(nM, c1_cols)[:, :cap1][ad, idx]
            tok = tok.astype(jnp.float32) * jnp.where(ok, sv, 1.0)[:, None]
        tok = tok.reshape(T, k, H)
        return jnp.sum(tok.astype(jnp.float32)
                       * w[..., None].astype(jnp.float32),
                       axis=1).astype(a2a.dtype)

    return ctx.shard_map(
        gather, in_specs=(both,) * (5 + (1 if wire is not None else 0)),
        out_specs=both)(
        back1, a_dst, slot1, ok1, topk_weights, *b1sc)


__all__ = ["all_to_all_push", "all_to_all_push_seg", "EpAllToAllContext",
           "create_all_to_all_context", "route_tokens", "dispatch", "combine",
           "Ep2dAllToAllContext", "create_all_to_all_context_2d",
           "route_tokens_2d", "dispatch_2d", "combine_2d", "a2a_wire_bytes",
           "pick_wire_dtype"]
