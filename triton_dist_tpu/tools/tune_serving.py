"""Tune the serving fleet's r6-lever kernels IN CONTEXT and persist the
winners (ISSUE 15: the tuning half of the zero-trace cold-start story).

``contextual_autotune`` picks winners per process; this driver makes the
sweep *representative* and *durable*: it replays a control journal's actual
traffic (prompt lengths, arrival widths) to derive the operand shapes the
fleet really dispatches, sweeps each autotuned overlap op's candidate list
at those shapes on the real serving mesh, and records every winner into a
sigcheck-gated :class:`~triton_dist_tpu.aot.registry.TunedConfigRegistry`
saved as JSON — the file ``tools/compile_aot.py --registry`` embeds into
the artifact and every later replica reads back as its first candidate
(the ``registry_hit`` fast path, no re-sweep).

Usage::

    python -m triton_dist_tpu.tools.tune_serving \
        --journal journal-r0.jsonl --out tuned.json \
        --world 4 --d-model 4096 --d-ff 14336 [--ops ag_gemm,gemm_rs]
"""

from __future__ import annotations

import argparse
import json
import sys


def traffic_shapes(entries, world: int, d_model: int,
                   max_tokens: int = 8192) -> dict:
    """Token-batch geometry from replayed journal traffic: the pow2 bucket
    of the busiest step's submitted tokens (clamped to a tile-friendly
    floor) — the M every swept GEMM sees."""
    per_step: dict[int, int] = {}
    n_reqs = 0
    for e in entries:
        if e.get("kind") != "submit":
            continue
        n_reqs += 1
        per_step[e["step"]] = (per_step.get(e["step"], 0)
                               + len(e.get("prompt", ())))
    peak = max(per_step.values()) if per_step else 0
    floor = world * 32                      # smallest candidate tile per rank
    m = floor
    while m < min(max(peak, floor), max_tokens):
        m *= 2
    # d_model floors at 128: the wire-lane/tile minimum every kernel assumes
    return {"M": m, "K": max(d_model, 128), "requests": n_reqs,
            "peak_step_tokens": peak}


def sweep(ctx, shapes: dict, ops, d_ff: int,
          log=lambda s: None) -> list:
    """Run each requested autotuned wrapper once at the traffic-derived
    shapes; the installed default registry records each winner (or the
    ``registry_hit`` marker when a prior run already persisted one). An op
    whose kernel cannot execute on this backend (the 0.4.x generic
    interpreter has no cross-device semaphore model — ops/all_to_all.py
    ``_interp_supports_remote_dma``) is logged and skipped, never fatal.
    Returns the list of ops that completed."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops import autotuned as at

    n = ctx.num_ranks
    M, K = shapes["M"], shapes["K"]
    N = max(d_ff, 128)
    key = jax.random.key(0)
    done = []

    def attempt(op, thunk, desc):
        if op not in ops:
            return
        try:
            thunk()
            done.append(op)
            log(f"{op} swept at {desc}")
        except Exception as e:
            log(f"{op} SKIPPED ({desc}): {type(e).__name__}: {e}")

    attempt("ag_gemm", lambda: at.ag_gemm_autotuned(
        ctx,
        ctx.shard(jax.random.normal(key, (M, K), jnp.float32), P("x")),
        ctx.shard(jax.random.normal(key, (K, (N // n) * n), jnp.float32),
                  P(None, "x")), "x"),
        f"M={M} K={K} N={(N // n) * n}")
    kk = (K // n) * n
    attempt("gemm_rs", lambda: at.gemm_rs_autotuned(
        ctx,
        ctx.shard(jax.random.normal(key, (M, kk), jnp.float32),
                  P(None, "x")),
        ctx.shard(jax.random.normal(key, (kk, N), jnp.float32), P("x")),
        "x"), f"M={M} K={kk} N={N}")
    s = max(M, n * 512)
    q = jax.random.normal(key, (1, 2, s, 128), jnp.float32)
    attempt("ring_attention", lambda: at.ring_attention_autotuned(
        ctx, ctx.shard(q, P(None, None, "x")),
        ctx.shard(q, P(None, None, "x")),
        ctx.shard(q, P(None, None, "x")), "x"), f"S={s} D=128")

    # local (single-device) grouped-GEMM levers: mesh_shape=() keys, no
    # signal protocol — these execute on every backend including the
    # generic interpreter, so a CPU tuning box still produces a registry
    e_cnt, tokens = 4, jax.random.normal(key, (M, K), jnp.float32)
    ids = jnp.arange(M, dtype=jnp.int32) % e_cnt
    w = jax.random.normal(key, (e_cnt, K, N), jnp.float32)
    attempt("grouped_gemm", lambda: at.grouped_gemm_autotuned(
        tokens, ids, w), f"T={M} H={K} N={N} E={e_cnt}")
    wd = jax.random.normal(key, (e_cnt, N, K), jnp.float32)
    attempt("moe_ffn_gated", lambda: at.moe_ffn_gated_autotuned(
        tokens, ids, w, w, wd), f"T={M} H={K} F={N} E={e_cnt}")
    return done


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Sweep serving-lever kernel configs at journal-derived "
                    "traffic shapes; persist winners to a tuned-config "
                    "registry")
    ap.add_argument("--journal", help="control journal jsonl to replay "
                                      "(omit for the synthetic default "
                                      "trace)")
    ap.add_argument("--out", required=True, help="registry JSON to write")
    ap.add_argument("--world", type=int, default=4,
                    help="ranks on the tuning mesh (virtual CPU devices "
                         "are forced to match)")
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--d-ff", type=int, default=512)
    ap.add_argument("--ops",
                    default="ag_gemm,gemm_rs,grouped_gemm,moe_ffn_gated",
                    help="comma list: ag_gemm,gemm_rs,ring_attention,"
                         "grouped_gemm,moe_ffn_gated")
    ap.add_argument("--no-sigcheck", action="store_true",
                    help="admit winners ungated (NOT for production "
                         "registries)")
    args = ap.parse_args(argv)

    from triton_dist_tpu.utils.env import force_virtual_cpu_devices
    force_virtual_cpu_devices(args.world, skip_if_satisfied=True)

    if args.journal:
        from triton_dist_tpu.serving.journal import ControlJournal
        entries = ControlJournal.load(args.journal).entries()
    else:
        # synthetic default: 16 requests, 2/step, 3-16 token prompts
        import numpy as np
        rng = np.random.RandomState(7)
        entries = [{"kind": "submit", "step": i // 2,
                    "prompt": [1] * int(rng.randint(3, 17))}
                   for i in range(16)]

    from triton_dist_tpu.aot.registry import (TunedConfigRegistry,
                                              set_default_registry)
    from triton_dist_tpu.shmem.context import initialize_distributed

    ctx = initialize_distributed(axis_names=("x",),
                                 mesh_shape=(args.world,))
    shapes = traffic_shapes(entries, args.world, args.d_model)
    # incremental tuning: an existing --out is loaded first, so re-runs at
    # already-covered (op, mesh, dtype, bucket) keys take the registry_hit
    # fast path and only NEW shapes pay a sweep
    import os
    reg = (TunedConfigRegistry.load(
               args.out, require_sigcheck=not args.no_sigcheck)
           if os.path.isfile(args.out)
           else TunedConfigRegistry(require_sigcheck=not args.no_sigcheck))
    set_default_registry(reg)
    try:
        done = sweep(ctx, shapes,
                     [o.strip() for o in args.ops.split(",") if o],
                     args.d_ff,
                     log=lambda s: print(f"[tune] {s}", file=sys.stderr))
    finally:
        set_default_registry(None)
    reg.save(args.out)

    print(json.dumps({
        "out": args.out,
        "swept": done,
        "entries": len(reg),
        "keys": [k.to_json() for k in reg.keys()],
        "traffic": shapes,
        "hit_rate": round(reg.hit_rate, 3),
    }, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
