"""Continuous-batching scheduler: FIFO admission into fixed batch slots,
prefill/decode interleaving, preemption-by-eviction when the KV pool runs
dry.

TPU-shaped by construction: the engine's decode step is ONE compiled
kernel over ``num_slots`` batch rows, so the scheduler never changes
shapes — it only decides which request occupies which slot and which
slots are active this step (inactive rows are masked by parking them on
the engine's scratch page). Policy lives here; mechanics (page
allocation, prefill handoff, the jitted step) live in ``engine.py``.

Policies (all deterministic — bit-identical replay is a test invariant):

- **admission**: strict FIFO. A request is admitted when a slot is free
  AND the pool can hold its whole prompt; admission stops at the first
  request that does not fit (no reordering — small requests cannot
  starve a big head-of-line request).
- **preemption**: when decode growth finds the pool dry, evict the
  YOUNGEST active request (latest admission wins the victim lottery —
  it has the least sunk prefill+decode work), free its pages, requeue it
  at the FRONT of the queue so it reclaims a slot as soon as pressure
  clears. A preempted request restarts from its prompt: greedy decode is
  deterministic, so the regenerated tokens are identical to the lost
  ones (tests assert bit-equality against uncontended runs).
"""

from __future__ import annotations

import dataclasses
import enum
from collections import deque

from triton_dist_tpu.serving.deadline import Deadline


class AdmissionRejected(RuntimeError):
    """Typed overload terminal (ISSUE 9): the bounded admission queue was
    at capacity when the request arrived. The request never held a slot or
    a page — rejecting it is free and keeps queue wait bounded, which the
    TTL below turns into a hard latency contract."""


class TtlExpired(AdmissionRejected):
    """Typed overload terminal (ISSUE 9): the request sat in the admission
    queue past its ``Deadline`` without ever being admitted. Only
    never-admitted requests expire — once a request is admitted it is
    carried to completion (possibly through preemptions), so 'every
    admitted request finishes bit-identically' stays an invariant under
    overload."""


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILLING = "prefilling"          # holds a slot + pages, chunk cursor
    # disaggregated handoff (ISSUE 6): prefill is DONE (first token known,
    # prefill-side pages freed) but the request sits on the decode worker
    # waiting for the signals covering its migrated pages to fire —
    # signal-gated admission flips it to ACTIVE, never the host clock
    MIGRATING = "migrating"
    ACTIVE = "active"
    FINISHED = "finished"
    # per-request failure domain (ISSUE 7): the recovery ladder (deadline
    # -> bounded retry -> local re-prefill degradation) ran dry for THIS
    # request. Its pages are freed, ``failure`` carries the typed reason
    # (with the ledger dump), and the engine keeps serving everyone else —
    # a failed request never takes the engine down with it.
    FAILED = "failed"
    # overload terminal (ISSUE 9): rejected at submit (bounded admission
    # queue at capacity) or expired in the queue past its TTL deadline —
    # the request never held a slot or a page. ``failure`` carries the
    # typed AdmissionRejected/TtlExpired reason. Appended AFTER the
    # pre-existing states so their digest indices are unchanged.
    REJECTED = "rejected"


@dataclasses.dataclass
class Request:
    """One serving request plus its runtime bookkeeping."""
    rid: int
    prompt: tuple[int, ...]            # token ids
    max_new_tokens: int
    eos_token: int | None = None       # finish early when generated
    state: RequestState = RequestState.QUEUED
    generated: list[int] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    admitted_seq: int = -1             # admission ticket (victim ordering)
    submit_step: int = -1              # engine step counters for metrics
    first_token_step: int = -1
    finish_step: int = -1
    submit_time: float | None = None   # wall clocks for TTFT
    first_token_time: float | None = None
    # chunked-prefill state (engine's PREFILLING state machine): prompt
    # tokens whose KV is already in pages. Survives mid-prefill eviction —
    # the request requeues AT ITS CURSOR (with its filled pages) and
    # resumes there, not at the prompt start. The TTFT split clocks ride
    # along: queue time = submit → first admission, prefill time = first
    # admission → first token.
    prefill_cursor: int = 0
    prefill_start_step: int = -1
    prefill_start_time: float | None = None
    # disaggregated handoff (ISSUE 6): the first token rides the HOST
    # control plane from the prefill worker (it was argmaxed on the
    # prefill device by the final chunk); everything bulky — the KV pages
    # — moves device-to-device through the migration kernel instead.
    # None until the final prefill chunk lands; reset on decode-side
    # preemption (full re-prefill recomputes it bit-identically).
    first_token: int | None = None
    # recovery ladder bookkeeping (ISSUE 7): how many times this request's
    # migration was re-sent after a signal deadline expired, how many
    # times it fell back to decode-local re-prefill, and — terminal —
    # the typed exception that FAILED it (None while alive). The per-
    # request twins of the engine-level retries/degradations counters.
    retries: int = 0
    degradations: int = 0
    failure: Exception | None = None
    # bounded-queue TTL (ISSUE 9): armed by the engine at submit when
    # ``ttl_steps`` is configured; ``expire()`` sweeps never-admitted
    # queued requests whose deadline has passed. None = no TTL.
    deadline: Deadline | None = None
    # prefix cache (ISSUE 13): prompt tokens served by adopting cached
    # pages at first admission (0 = cold). Drives the cached-vs-cold
    # TTFT split; re-admissions after preemption keep the original value
    # (the clock, like the hit, belongs to the first admission).
    cache_hit_tokens: int = 0

    @property
    def kv_len(self) -> int:
        """Tokens holding KV right now: prompt + all but the newest
        generated token (the newest one's KV is written by the step that
        consumes it)."""
        return len(self.prompt) + max(len(self.generated) - 1, 0)

    @property
    def done(self) -> bool:
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (self.eos_token is not None and bool(self.generated)
                and self.generated[-1] == self.eos_token)

    @property
    def remaining(self) -> int:
        """Token budget left (0 once done — EOS or max_new_tokens)."""
        return 0 if self.done else self.max_new_tokens - len(self.generated)


class ContinuousBatchingScheduler:
    """Slot + queue state machine. The engine calls, in step order:
    ``admissible()`` → prefill each admitted request → ``activate()``,
    then ``pick_victim()`` whenever growth fails, then ``finish()`` as
    slots complete."""

    def __init__(self, num_slots: int, queue_cap: int | None = None):
        assert num_slots >= 1
        self.num_slots = num_slots
        self.queue_cap = queue_cap
        self.queue: deque[Request] = deque()
        self.slots: list[Request | None] = [None] * num_slots
        self._admit_ticket = 0

    # -- queue ------------------------------------------------------------
    def submit(self, req: Request, front: bool = False) -> None:
        (self.queue.appendleft if front else self.queue.append)(req)

    # -- bounded admission (ISSUE 9) --------------------------------------
    @property
    def at_capacity(self) -> bool:
        """True when a NEW submission must be rejected. Preemption requeues
        (``front=True``) are exempt — an admitted request always keeps its
        place in line, only fresh arrivals are shed."""
        return self.queue_cap is not None and len(self.queue) >= self.queue_cap

    def expire(self, now: int) -> list[Request]:
        """Sweep never-admitted queued requests whose TTL ``Deadline`` has
        passed at step ``now``. Expired requests are removed from the queue
        and flipped to REJECTED; the engine attaches the typed failure and
        counts them. Requests that have ever been admitted
        (``admitted_seq >= 0``, i.e. preemption requeues) never expire —
        their work is carried to completion."""
        expired = [r for r in self.queue
                   if r.admitted_seq < 0 and r.deadline is not None
                   and r.deadline.expired(now)]
        for r in expired:
            self.queue.remove(r)
            r.state = RequestState.REJECTED
        return expired

    def digest(self) -> int:
        """Order-sensitive 32-bit FNV-1a digest of the WHOLE scheduling
        state: queue order (with each request's resume-relevant cursors),
        slot seating, and the admission ticket. The scheduler half of the
        replicated-decision guard (see ``KVPagePool.digest``): sharded
        serving runs one scheduler instance per rank and asserts the
        digests match every step — a forked admission or victim choice is
        caught before its block tables diverge, not after."""
        from triton_dist_tpu.serving.kv_pool import _fnv1a
        h = _fnv1a(0x811C9DC5, self.num_slots, self._admit_ticket,
                   len(self.queue))
        for r in self.queue:
            h = _fnv1a(h, r.rid, r.prefill_cursor, r.preemptions,
                       len(r.generated))
        for r in self.slots:
            if r is None:
                h = _fnv1a(h, 0xFFFFFFFF)
            else:
                h = _fnv1a(h, r.rid, list(RequestState).index(r.state),
                           r.admitted_seq, r.prefill_cursor,
                           len(r.generated))
        return h

    @property
    def queue_depth(self) -> int:
        return len(self.queue)

    @property
    def active(self) -> list[tuple[int, Request]]:
        return [(i, r) for i, r in enumerate(self.slots) if r is not None]

    @property
    def idle(self) -> bool:
        return not self.queue and all(s is None for s in self.slots)

    def free_slot(self) -> int | None:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    # -- admission --------------------------------------------------------
    def admissible(self, pool_can_hold) -> tuple[int, Request] | None:
        """Next (slot, request) to admit, or None. ``pool_can_hold(req)``
        is the engine's pages-available check; FIFO order is strict — a
        head-of-line request that does not fit blocks admission (it will
        fit once finishes/preemptions release pages)."""
        slot = self.free_slot()
        if slot is None or not self.queue:
            return None
        req = self.queue[0]
        if not pool_can_hold(req):
            return None
        return slot, req

    def activate(self, slot: int, req: Request) -> None:
        assert self.slots[slot] is None and self.queue[0] is req
        self.queue.popleft()
        req.state = RequestState.ACTIVE
        req.admitted_seq = self._admit_ticket
        self._admit_ticket += 1
        self.slots[slot] = req

    # -- disaggregated handoff (ISSUE 6) ----------------------------------
    def place(self, slot: int, req: Request) -> None:
        """Seat a request arriving from the PEER role's scheduler (the
        decode worker seating a prefilling/migrating request). Unlike
        ``activate`` it does not touch the queue and does not change
        ``req.state`` — the disagg engine drives the PREFILLING →
        MIGRATING → ACTIVE handoff states itself — but it DOES take an
        admission ticket so victim ordering stays uniform across
        colocated and handed-off requests."""
        assert self.slots[slot] is None
        req.admitted_seq = self._admit_ticket
        self._admit_ticket += 1
        self.slots[slot] = req

    def remove(self, slot: int) -> Request:
        """Unseat WITHOUT requeue — the other half of the handoff verbs:
        a completed prefill leaves the prefill scheduler through here (it
        continues on the DECODE worker, not in this queue), and a decode-
        side victim is routed back to the PREFILL role's queue by the
        engine. State/cursor/requeue policy is entirely the caller's
        (contrast ``evict``, which requeues locally)."""
        req = self.slots[slot]
        assert req is not None
        self.slots[slot] = None
        return req

    # -- preemption -------------------------------------------------------
    def pick_victim(self, exclude_slot: int | None = None) -> int | None:
        """Youngest active slot (highest admission ticket), optionally
        excluding one slot (a grower never evicts itself while another
        victim exists — evicting self frees its own pages but forfeits
        more progress than evicting the youngest)."""
        best, best_ticket = None, -1
        for i, r in enumerate(self.slots):
            if r is None or i == exclude_slot:
                continue
            if r.admitted_seq > best_ticket:
                best, best_ticket = i, r.admitted_seq
        return best

    def evict(self, slot: int) -> Request:
        """Remove the slot's request and requeue it at the FRONT. A
        decoding request restarts from its prompt (greedy decode is
        deterministic — the regenerated tokens are bit-identical); a
        mid-prefill request keeps ``prefill_cursor`` — the ENGINE decides
        whether the cursor (and the pages behind it) survives or resets
        (engine._preempt: kept when there is an unfilled page tail to
        reclaim, reset to 0 otherwise)."""
        req = self.slots[slot]
        assert req is not None
        self.slots[slot] = None
        req.state = RequestState.QUEUED
        req.preemptions += 1
        req.generated.clear()
        self.submit(req, front=True)
        return req

    # -- completion -------------------------------------------------------
    def finish(self, slot: int) -> Request:
        req = self.slots[slot]
        assert req is not None and req.done
        self.slots[slot] = None
        req.state = RequestState.FINISHED
        return req


__all__ = ["Request", "RequestState", "ContinuousBatchingScheduler",
           "AdmissionRejected", "TtlExpired"]
