"""tpushmem primitive tests — notify/wait ping-pong and one-sided puts.

Parity targets: reference tutorial 01 (producer/consumer notify+wait),
test/nvidia/test_notify.py, test_distributed_wait.py, test_ring_put.py.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.shmem import device as shd
from conftest import TEST_WORLD
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose, default_interpret


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


@pytest.mark.quick
def test_ring_put(ctx):
    """Each PE puts its shard to its right neighbor; receiver waits the DMA
    recv semaphore (= notify/wait of tutorial 01)."""

    def kernel(in_ref, out_ref, send_sem, recv_sem):
        me = shd.my_pe("x")
        n = shd.n_pes("x")
        dst = jax.lax.rem(me + 1, n)
        rdma = shd.putmem_nbi(out_ref, in_ref, send_sem, recv_sem, dst)
        shd.quiet(rdma)
        shd.wait_recv(out_ref, recv_sem)  # delivery of left neighbor's put

    def f(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                                 collective_id=0),
            interpret=default_interpret(),
        )(x)

    n = ctx.num_ranks
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    y = jax.jit(ctx.shard_map(f, in_specs=P("x"), out_specs=P("x")))(x)
    want = np.roll(np.asarray(x), 8, axis=0)  # shard shift by one PE
    assert_allclose(y, want)


def test_notify_wait_pingpong(ctx):
    """Multi-round producer/consumer: K rounds of put-accumulate around the
    ring; exercises repeated signal_wait_until on the same semaphore
    (counting semantics) and quiet()."""
    ROUNDS = 4

    def kernel(in_ref, out_ref, acc, send_sem, recv_sem):
        me = shd.my_pe("x")
        n = shd.n_pes("x")
        dst = jax.lax.rem(me + 1, n)

        def round_body(r, _):
            # send current accumulator to right neighbor's out_ref
            rdma = shd.putmem_nbi(out_ref, acc, send_sem, recv_sem, dst)
            shd.quiet(rdma)
            shd.wait_recv(out_ref, recv_sem)
            pltpu.sync_copy(out_ref, acc)
            acc[...] = acc[...] + 1.0
            # all PEs must finish the round before the buffer is overwritten
            shd.barrier_all(("x",))
            return 0

        pltpu.sync_copy(in_ref, acc)
        jax.lax.fori_loop(0, ROUNDS, round_body, 0)
        pltpu.sync_copy(acc, out_ref)

    def f(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM(x.shape, x.dtype),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
            ],
            compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                                 collective_id=1),
            interpret=default_interpret(),
        )(x)

    n = ctx.num_ranks
    shard_rows = 8
    x = jnp.tile(jnp.arange(n, dtype=jnp.float32)[:, None, None],
                 (1, shard_rows, 128)).reshape(n * shard_rows, 128)
    sm = ctx.shard_map(
        functools.partial(f),
        in_specs=P("x"), out_specs=P("x"))
    y = jax.jit(sm)(x)

    # golden: value rotates one step per round, +1 each round
    vals = np.arange(n, dtype=np.float32)
    for _ in range(ROUNDS):
        vals = np.roll(vals, 1) + 1.0
    want = np.tile(vals[:, None, None], (1, shard_rows, 128)).reshape(n * shard_rows, 128)
    assert_allclose(y, want)


@pytest.mark.quick
def test_barrier_all(ctx):
    """barrier_all: late PEs' pre-barrier writes must be visible to a remote
    read issued after the barrier (here: everyone puts before barrier, reads
    after)."""

    def kernel(in_ref, out_ref, scratch, send_sem, recv_sem):
        me = shd.my_pe("x")
        n = shd.n_pes("x")
        dst = jax.lax.rem(me + 1, n)
        rdma = shd.putmem_nbi(out_ref, in_ref, send_sem, recv_sem, dst)
        shd.quiet(rdma)
        shd.wait_recv(out_ref, recv_sem)
        shd.barrier_all(("x",))
        pltpu.sync_copy(out_ref, scratch)
        scratch[...] = scratch[...] * 2.0
        pltpu.sync_copy(scratch, out_ref)

    def f(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.VMEM(x.shape, x.dtype),
                            pltpu.SemaphoreType.DMA(()),
                            pltpu.SemaphoreType.DMA(())],
            compiler_params=pltpu.CompilerParams(has_side_effects=True,
                                                 collective_id=2),
            interpret=default_interpret(),
        )(x)

    n = ctx.num_ranks
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    y = jax.jit(ctx.shard_map(f, in_specs=P("x"), out_specs=P("x")))(x)
    want = np.roll(np.asarray(x), 8, axis=0) * 2.0
    assert_allclose(y, want)


def test_symm_tensor_shape(ctx):
    buf = ctx.create_symm_tensor((4, 128), jnp.bfloat16)
    assert buf.shape == (ctx.num_ranks, 4, 128)
    assert buf.dtype == jnp.bfloat16
