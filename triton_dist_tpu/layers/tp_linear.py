"""Tensor-parallel linear layers over the overlap kernels — the module-level
API the reference exposes through tutorials 07/08 (AG-GEMM forward,
GEMM-RS forward) rather than as classes; provided as first-class layers
here."""

from __future__ import annotations

import dataclasses

import jax

from triton_dist_tpu.ops.allgather_gemm import ag_gemm
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs
from triton_dist_tpu.shmem.context import ShmemContext


@dataclasses.dataclass(frozen=True)
class ColumnParallelLinear:
    """y = all_gather(x) @ W with W column-sharded — the Megatron-style
    first TP linear, computed by the AG-GEMM overlap kernel
    (cf. reference allgather_gemm.py:835-880)."""
    ctx: ShmemContext
    axis: str | None = None
    cfg: GemmConfig | None = None

    def __call__(self, x: jax.Array, w: jax.Array, out_dtype=None):
        return ag_gemm(self.ctx, x, w, axis=self.axis, cfg=self.cfg,
                       out_dtype=out_dtype)


@dataclasses.dataclass(frozen=True)
class RowParallelLinear:
    """y = reduce_scatter(x @ W) with W row-sharded — the second TP linear,
    computed by the GEMM-RS overlap kernel
    (cf. reference gemm_reduce_scatter.py:524-538)."""
    ctx: ShmemContext
    axis: str | None = None
    cfg: GemmConfig | None = None

    def __call__(self, x: jax.Array, w: jax.Array, out_dtype=None):
        return gemm_rs(self.ctx, x, w, axis=self.axis, cfg=self.cfg,
                       out_dtype=out_dtype)
