"""Trace-time event capture: replay kernels per rank, record the protocol.

The capture replays an op's ``shard_map`` body ONCE PER RANK, sequentially,
with concrete rank coordinates and numpy-backed fake refs. Every shmem
primitive (via :mod:`triton_dist_tpu.shmem.trace`) and every raw Pallas
DMA/semaphore call (via monkeypatched ``pl``/``pltpu`` attributes) appends
a symbolic :class:`~.events.Event` instead of emitting a Mosaic op. Waits
record but do not block — cross-rank feasibility (deadlock, starvation) is
decided afterwards by :mod:`.checker`'s simulation over the recorded
streams.

Sequential replay is sound here because no kernel in this repo makes a
*protocol* decision based on data received from a remote put: peers,
semaphores, increments and regions depend only on the rank's own inputs,
scalar prefetch and shapes. Remote payloads may therefore be garbage
(zeros) during capture without changing the recorded event structure.

Capture runs under ``TDT_FORCE_COMPILED=1`` so every op builds its real
one-sided protocol (not an interpret-mode mirror), and with
``TDT_NOISE``/``TDT_SERIAL`` cleared so debug modes don't distort it.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..shmem import trace
from .events import Event, Region, SemId


def _as_int(x) -> int:
    return int(np.asarray(x))


def _prod(xs) -> int:
    out = 1
    for x in xs:
        out *= int(x)
    return out


# -- fake buffers and refs ---------------------------------------------------

class BufferInfo:
    """One concrete buffer: stable (per-rank-deterministic) id + np storage."""

    def __init__(self, buf_id: str, array: np.ndarray):
        self.id = buf_id
        self.array = array


class _At:
    def __init__(self, ref: "FakeRef"):
        self._ref = ref

    def __getitem__(self, idx) -> "FakeRef":
        return FakeRef(self._ref.info, self._ref._resolve(idx))


class FakeRef:
    """View into a :class:`BufferInfo`: per-base-dimension ``(start, size,
    keep)`` selection (``keep=False`` marks integer-indexed, squeezed dims).
    Reads/writes record events on the active tracer and move real numpy
    data, so host-level glue around the kernels keeps working."""

    def __init__(self, info: BufferInfo, sel=None):
        self.info = info
        self.sel = sel if sel is not None else tuple(
            (0, d, True) for d in info.array.shape)

    # ---- geometry

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(sz for (_, sz, keep) in self.sel if keep)

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def dtype(self):
        return self.info.array.dtype

    @property
    def nbytes(self) -> int:
        return _prod(self.shape) * self.info.array.dtype.itemsize

    @property
    def at(self) -> _At:
        return _At(self)

    def region(self) -> Region:
        return Region(self.info.id,
                      tuple((st, st + sz) for (st, sz, _) in self.sel))

    def _np_index(self):
        return tuple(slice(st, st + sz) if keep else st
                     for (st, sz, keep) in self.sel)

    def _resolve(self, idx):
        if not isinstance(idx, tuple):
            idx = (idx,)
        visible = [i for i, (_, _, keep) in enumerate(self.sel) if keep]
        if any(e is Ellipsis for e in idx):
            pos = next(i for i, e in enumerate(idx) if e is Ellipsis)
            pad = len(visible) - (len(idx) - 1)
            idx = idx[:pos] + (slice(None),) * pad + idx[pos + 1:]
        idx = idx + (slice(None),) * (len(visible) - len(idx))
        if len(idx) > len(visible):
            raise IndexError(
                f"sigcheck capture: {len(idx)} indices into rank-"
                f"{len(visible)} ref {self.info.id}")
        newsel = list(self.sel)
        for elem, d in zip(idx, visible):
            st, sz, _ = self.sel[d]
            if hasattr(elem, "start") and hasattr(elem, "size"):
                # pl.ds / pallas Slice
                newsel[d] = (st + _as_int(elem.start), _as_int(elem.size),
                             True)
            elif isinstance(elem, slice):
                if elem.step not in (None, 1):
                    raise NotImplementedError(
                        "sigcheck capture: strided ref slices unsupported")
                lo = 0 if elem.start is None else _as_int(elem.start)
                hi = sz if elem.stop is None else _as_int(elem.stop)
                if lo < 0:
                    lo += sz
                if hi < 0:
                    hi += sz
                newsel[d] = (st + lo, hi - lo, True)
            else:
                i = _as_int(elem)
                if i < 0:
                    i += sz
                newsel[d] = (st + i, 1, False)
        return tuple(newsel)

    # ---- data access (records events)

    def __getitem__(self, idx):
        sub = FakeRef(self.info, self._resolve(idx))
        t = trace.active_tracer()
        if t is not None:
            t.record_read(sub)
        return self.info.array[sub._np_index()]

    def __setitem__(self, idx, value):
        sub = FakeRef(self.info, self._resolve(idx))
        t = trace.active_tracer()
        if t is not None:
            t.record_write(sub)
        self.info.array[sub._np_index()] = np.asarray(value)


class FakeSem:
    """Semaphore allocation (cell array): symbolic identity + local int64
    counts. Counts only mirror *local* effects (self-signals, local DMA
    credits) so ``signal_read`` polls stay meaningful; the cross-rank
    arithmetic lives in the checker."""

    def __init__(self, alloc: str, shape: Tuple[int, ...], kind: str,
                 counts: np.ndarray | None = None, sel=None):
        self.alloc = alloc
        self.base_shape = tuple(shape)
        self.kind = kind
        self.counts = counts if counts is not None else np.zeros(
            self.base_shape, np.int64)
        self.sel = sel if sel is not None else tuple(
            (0, d, True) for d in self.base_shape)

    @property
    def at(self):
        return _SemAt(self)

    def _narrow(self, idx):
        helper = FakeRef(BufferInfo(self.alloc, self.counts), self.sel)
        return FakeSem(self.alloc, self.base_shape, self.kind, self.counts,
                       helper._resolve(idx))

    def cell(self) -> SemId:
        coords = []
        for (st, sz, _) in self.sel:
            if sz != 1:
                raise NotImplementedError(
                    f"sigcheck capture: semaphore {self.alloc} used with "
                    f"unresolved cell range {self.sel}")
            coords.append(st)
        return SemId(self.alloc, tuple(coords), self.kind)

    def _cell_index(self):
        return tuple(st for (st, _, _) in self.sel)

    def add(self, inc: int):
        self.counts[self._cell_index()] += inc

    def read(self) -> int:
        return int(self.counts[self._cell_index()])


class _SemAt:
    def __init__(self, sem: FakeSem):
        self._sem = sem

    def __getitem__(self, idx) -> FakeSem:
        return self._sem._narrow(idx)


# -- DMA descriptors ---------------------------------------------------------

class FakeRDMA:
    """Descriptor returned by a captured ``putmem_nbi``."""

    def __init__(self, tracer: "RankTracer", rdma_id: int, dst_ref: FakeRef,
                 recv_sem: FakeSem, send_sem: Optional[FakeSem],
                 nbytes: int):
        self._tracer = tracer
        self._id = rdma_id
        self._dst = dst_ref
        self._recv = recv_sem
        self._send = send_sem
        self._nbytes = nbytes

    def wait_send(self):
        # draining the send sem consumes the source-side credit the put made
        if self._send is not None:
            self._tracer._emit("wait_send", rdma_id=self._id,
                               sem=self._send.cell(), value=self._nbytes)
        else:
            self._tracer._emit("wait_send", rdma_id=self._id)

    def wait(self):
        # a full .wait() on a remote copy waits send AND (local) recv — the
        # local recv sem is credited by the symmetric peer's incoming put
        self.wait_send()
        self._tracer.wait_recv(self._dst, self._recv)


class _PendingRemoteCopy:
    """Patched ``pltpu.make_async_remote_copy``: records on .start()."""

    def __init__(self, tracer, src_ref, dst_ref, send_sem, recv_sem,
                 device_id):
        self._args = (tracer, src_ref, dst_ref, send_sem, recv_sem, device_id)
        self._rdma: FakeRDMA | None = None

    def start(self):
        tracer, src, dst, send, recv, pe = self._args
        self._rdma = tracer.putmem_nbi(dst, src, send, recv, pe)
        return self._rdma

    def _started(self) -> FakeRDMA:
        if self._rdma is None:
            raise RuntimeError("sigcheck capture: wait before start on a "
                               "remote copy descriptor")
        return self._rdma

    def wait_send(self):
        self._started().wait_send()

    def wait(self):
        self._started().wait()


class FakeCopy:
    """Patched ``pltpu.make_async_copy``: local async copy (start/wait) or
    the same-ref ``wait_recv`` trick (wait only)."""

    def __init__(self, tracer, src_ref, dst_ref, sem):
        self._tracer = tracer
        self._src = src_ref
        self._dst = dst_ref
        self._sem = sem

    def start(self):
        self._tracer.local_copy_start(self._src, self._dst, self._sem)

    def wait(self):
        self._tracer.wait_recv(self._dst, self._sem)


# -- per-rank tracer ---------------------------------------------------------

class _CallCtx:
    def __init__(self, key: str, collective_id, grid_dims: Tuple[int, ...]):
        self.key = key
        self.collective_id = collective_id
        self.grid_dims = grid_dims
        self.grid_pos: Tuple[int, ...] = ()


class RankTracer:
    """Implements the ``shmem.trace`` hook protocol for one rank and records
    the event stream while that rank's replay runs."""

    def __init__(self, state: "CaptureState", coords: Dict[str, int]):
        self.state = state
        self.coords = dict(coords)
        self.flat = state.flat(coords)
        self.events: List[Event] = []
        self.seq = 0
        self.call_index = 0
        self.rdma_index = 0
        self.call_stack: List[_CallCtx] = []
        self.barrier_sems: Dict[str, FakeSem] = {}

    # ---- bookkeeping

    def _grid(self):
        return self.call_stack[-1].grid_pos if self.call_stack else None

    def _site(self):
        return self.call_stack[-1].key if self.call_stack else "<host>"

    def _emit(self, kind: str, **kw) -> Event:
        e = Event(rank=self.flat, seq=self.seq, kind=kind, grid=self._grid(),
                  site=self._site(), **kw)
        self.seq += 1
        self.events.append(e)
        return e

    def push_call(self, name: str, collective_id,
                  grid_dims: Tuple[int, ...]) -> _CallCtx:
        key = f"c{self.call_index}:{name}"
        self.call_index += 1
        ctx = _CallCtx(key, collective_id, grid_dims)
        self.call_stack.append(ctx)
        return ctx

    def pop_call(self):
        self.call_stack.pop()

    def barrier_sem(self, collective_id) -> FakeSem:
        alloc = f"barrier:{collective_id}"
        sem = self.barrier_sems.get(alloc)
        if sem is None:
            sem = FakeSem(alloc, (), "barrier")
            self.barrier_sems[alloc] = sem
        return sem

    # ---- data events

    def record_read(self, ref: FakeRef):
        self._emit("read", src=ref.region())

    def record_write(self, ref: FakeRef):
        self._emit("write", dst=ref.region())

    # ---- shmem.device hook protocol

    def putmem_nbi(self, dst_ref, src_ref, send_sem, recv_sem, pe) -> FakeRDMA:
        pe = _as_int(pe)
        rdma_id = self.rdma_index
        self.rdma_index += 1
        nbytes = src_ref.nbytes
        self._emit("put", src=src_ref.region(), dst=dst_ref.region(),
                   dst_rank=pe, sem=recv_sem.cell(),
                   send_sem=send_sem.cell() if send_sem is not None else None,
                   value=nbytes, rdma_id=rdma_id)
        if pe == self.flat:
            dst_ref.info.array[dst_ref._np_index()] = (
                src_ref.info.array[src_ref._np_index()].reshape(dst_ref.shape))
            recv_sem.add(nbytes)
        return FakeRDMA(self, rdma_id, dst_ref, recv_sem, send_sem, nbytes)

    def local_copy_start(self, src_ref, dst_ref, sem):
        rdma_id = self.rdma_index
        self.rdma_index += 1
        nbytes = src_ref.nbytes
        self._emit("put", src=src_ref.region(), dst=dst_ref.region(),
                   dst_rank=self.flat, sem=sem.cell(), value=nbytes,
                   rdma_id=rdma_id)
        if src_ref is not dst_ref:
            dst_ref.info.array[dst_ref._np_index()] = (
                src_ref.info.array[src_ref._np_index()].reshape(dst_ref.shape))
        sem.add(nbytes)

    def signal_op(self, sem_ref, inc, pe):
        inc = _as_int(inc)
        dst = self.flat if pe is None else _as_int(pe)
        self._emit("signal", sem=sem_ref.cell(), dst_rank=dst, value=inc)
        if dst == self.flat:
            sem_ref.add(inc)

    def signal_wait_until(self, sem_ref, value):
        v = _as_int(value)
        self._emit("wait", sem=sem_ref.cell(), value=v)
        sem_ref.add(-v)

    def wait_recv(self, dst_ref, recv_sem):
        nbytes = dst_ref.nbytes
        self._emit("wait_recv", dst=dst_ref.region(), sem=recv_sem.cell(),
                   value=nbytes)
        recv_sem.add(-nbytes)

    def signal_read(self, sem_ref):
        self._emit("sem_read", sem=sem_ref.cell())
        return jnp.int32(sem_ref.read())

    def quiet(self, *rdmas):
        for r in rdmas:
            r.wait_send()

    def fence(self):
        self._emit("fence")

    # ---- barriers (device.py routes here before touching Mosaic)

    def _pe_at_group(self, mesh_axes, group_axes, index: int) -> int:
        rem = index
        coords = {}
        for name in reversed(tuple(group_axes)):
            sz = self.state.sizes[name]
            coords[name] = rem % sz
            rem //= sz
        pid = 0
        for name in mesh_axes:
            pid = pid * self.state.sizes[name] + coords.get(
                name, self.coords[name])
        return pid

    def barrier_all(self, axis_names: Sequence[str],
                    mesh_axes: Sequence[str]):
        cid = (self.call_stack[-1].collective_id
               if self.call_stack else None)
        sem = self.barrier_sem(cid)
        npes = _prod(self.state.sizes[a] for a in axis_names)
        me = 0
        for name in axis_names:
            me = me * self.state.sizes[name] + self.coords[name]
        for i in range(npes):
            if i != me:
                pid = self._pe_at_group(mesh_axes, axis_names, i)
                self._emit("signal", sem=sem.cell(), dst_rank=pid, value=1)
        self._emit("wait", sem=sem.cell(), value=npes - 1)

    def barrier_pair(self, axis_names: Sequence[str], peer):
        cid = (self.call_stack[-1].collective_id
               if self.call_stack else None)
        sem = self.barrier_sem(cid)
        self._emit("signal", sem=sem.cell(), dst_rank=_as_int(peer), value=1)
        self._emit("wait", sem=sem.cell(), value=1)


# -- capture state + mesh ----------------------------------------------------

class CaptureState:
    def __init__(self, axes: Tuple[Tuple[str, int], ...]):
        self.axes = tuple(axes)
        self.sizes = dict(self.axes)
        self.n = _prod(sz for _, sz in self.axes)
        self.tracers: Dict[int, RankTracer] = {}
        self.cur: RankTracer | None = None

    def flat(self, coords: Dict[str, int]) -> int:
        pid = 0
        for name, sz in self.axes:
            pid = pid * sz + coords[name]
        return pid

    def unflatten(self, flat: int) -> Dict[str, int]:
        coords = {}
        for name, sz in reversed(self.axes):
            coords[name] = flat % sz
            flat //= sz
        return coords

    @contextlib.contextmanager
    def rank(self, coords: Dict[str, int]):
        flat = self.flat(coords)
        tracer = self.tracers.get(flat)
        if tracer is None:
            tracer = RankTracer(self, coords)
            self.tracers[flat] = tracer
        prev = self.cur
        self.cur = tracer
        trace.set_tracer(tracer)
        try:
            yield tracer
        finally:
            self.cur = prev
            trace.set_tracer(prev)

    def require(self) -> RankTracer:
        if self.cur is None:
            raise RuntimeError(
                "sigcheck capture: pallas/collective call outside a rank "
                "replay (op built work outside ctx.shard_map?)")
        return self.cur

    def streams(self) -> Dict[int, List[Event]]:
        return {r: t.events for r, t in sorted(self.tracers.items())}


# -- fake pallas_call --------------------------------------------------------

def _is_sem_scratch(s) -> bool:
    from jax.experimental.pallas import tpu as pltpu
    if isinstance(s, pltpu.SemaphoreType):
        return True
    dt = getattr(s, "dtype", None)
    return dt is not None and "sem" in str(dt)


def _sem_kind(s) -> str:
    from jax.experimental.pallas import tpu as pltpu
    if isinstance(s, pltpu.SemaphoreType):
        name = getattr(s, "name", str(s)).lower()
    else:
        name = str(getattr(s, "dtype", ""))
    if "dma" in name:
        return "dma"
    if "barrier" in name:
        return "barrier"
    return "regular"


def _spec_list(specs, count: int) -> list:
    if specs is None:
        return [None] * count
    if isinstance(specs, (list, tuple)):
        out = list(specs)
    else:
        out = [specs]
    if len(out) != count:
        raise NotImplementedError(
            f"sigcheck capture: {len(out)} block specs for {count} operands")
    return out


def _block_ref(info: BufferInfo, spec, grid_idx, prefetch_refs) -> FakeRef:
    block_shape = getattr(spec, "block_shape", None) if spec is not None \
        else None
    if block_shape is None:
        return FakeRef(info)
    index_map = getattr(spec, "index_map", None)
    if index_map is None:
        bidx = tuple(grid_idx)[:len(block_shape)]
    else:
        bidx = index_map(*grid_idx, *prefetch_refs)
    if not isinstance(bidx, tuple):
        bidx = (bidx,)
    if len(bidx) != len(block_shape):
        raise NotImplementedError(
            f"sigcheck capture: index_map arity {len(bidx)} vs block rank "
            f"{len(block_shape)}")
    sel = []
    for b, bs, dim in zip(bidx, block_shape, info.array.shape):
        if bs is None:
            sel.append((_as_int(b), 1, False))
        else:
            bs = int(bs)
            sel.append((_as_int(b) * bs, bs, True))
    return FakeRef(info, tuple(sel))


def _fake_pallas_call(state: CaptureState):
    def pallas_call(kernel, out_shape=None, *, grid_spec=None, grid=None,
                    in_specs=None, out_specs=None, scratch_shapes=(),
                    input_output_aliases=None, compiler_params=None,
                    name=None, **_ignored):
        def runner(*args):
            tracer = state.require()
            if grid_spec is not None:
                nsp = int(getattr(grid_spec, "num_scalar_prefetch", 0) or 0)
                g = getattr(grid_spec, "grid", ()) or ()
                ins = getattr(grid_spec, "in_specs", None)
                outs = getattr(grid_spec, "out_specs", None)
                scratch = getattr(grid_spec, "scratch_shapes", ()) or ()
            else:
                nsp = 0
                g = grid if grid is not None else ()
                ins = in_specs
                outs = out_specs
                scratch = scratch_shapes or ()
            if isinstance(g, int):
                g = (g,)
            g = tuple(int(x) for x in g)
            cid = getattr(compiler_params, "collective_id", None)
            call_name = name or getattr(kernel, "__name__", "kernel")

            out_leaves, out_tree = jax.tree_util.tree_flatten(out_shape)
            aliases = dict(input_output_aliases or {})

            call = tracer.push_call(call_name, cid, g)
            key = call.key
            try:
                arrays = [np.array(a, copy=True) for a in args]
                infos = [BufferInfo(f"{key}/in{j}", a)
                         for j, a in enumerate(arrays)]
                prefetch_refs = [FakeRef(infos[j]) for j in range(nsp)]
                data_infos = infos[nsp:]
                ins = _spec_list(ins, len(data_infos))
                outs = _spec_list(outs, len(out_leaves))

                out_infos = []
                for j, leaf in enumerate(out_leaves):
                    src = next((i for i, o in aliases.items() if o == j),
                               None)
                    if src is not None:
                        out_infos.append(infos[src])
                    else:
                        out_infos.append(BufferInfo(
                            f"{key}/out{j}",
                            np.zeros(leaf.shape, leaf.dtype)))

                scratch_objs = []
                for j, s in enumerate(scratch):
                    if _is_sem_scratch(s):
                        shp = tuple(getattr(s, "shape", ()) or ())
                        scratch_objs.append(
                            FakeSem(f"{key}/sem{j}", shp, _sem_kind(s)))
                    else:
                        shp = tuple(getattr(s, "shape", ()) or ())
                        dt = getattr(s, "dtype", np.float32)
                        scratch_objs.append(
                            FakeRef(BufferInfo(f"{key}/scratch{j}",
                                               np.zeros(shp, dt))))

                def invoke(grid_idx):
                    call.grid_pos = tuple(int(i) for i in grid_idx)
                    refs = list(prefetch_refs)
                    refs += [_block_ref(info, spec, grid_idx, prefetch_refs)
                             for info, spec in zip(data_infos, ins)]
                    refs += [_block_ref(info, spec, grid_idx, prefetch_refs)
                             for info, spec in zip(out_infos, outs)]
                    refs += scratch_objs
                    kernel(*refs)

                if not g:
                    invoke(())
                else:
                    for idx in np.ndindex(*g):
                        invoke(idx)
            finally:
                tracer.pop_call()

            results = [jnp.asarray(info.array) for info in out_infos]
            return jax.tree_util.tree_unflatten(out_tree, results)

        return runner

    return pallas_call


# -- patched jax surface -----------------------------------------------------

def _axis_total(state: CaptureState, axis_name) -> int:
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    return _prod(state.sizes[a] for a in names)


def _axis_flat_index(state: CaptureState, axis_name) -> int:
    tracer = state.require()
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    idx = 0
    for a in names:
        idx = idx * state.sizes[a] + tracer.coords[a]
    return idx


def _fake_collectives(state: CaptureState):
    def all_gather(x, axis_name, *, axis_index_groups=None, axis=0,
                   tiled=False, **_kw):
        n = _axis_total(state, axis_name)
        xs = [np.asarray(x)] * n
        return jnp.asarray(np.concatenate(xs, axis=axis) if tiled
                           else np.stack(xs, axis=axis))

    def psum(x, axis_name, *, axis_index_groups=None, **_kw):
        n = _axis_total(state, axis_name)
        return jax.tree_util.tree_map(lambda v: jnp.asarray(v) * n, x)

    def psum_scatter(x, axis_name, *, scatter_dimension=0,
                     axis_index_groups=None, tiled=False, **_kw):
        n = _axis_total(state, axis_name)
        me = _axis_flat_index(state, axis_name)
        full = np.asarray(x) * n
        if tiled:
            seg = full.shape[scatter_dimension] // n
            return jnp.asarray(np.take(
                full, range(me * seg, (me + 1) * seg),
                axis=scatter_dimension))
        return jnp.asarray(np.take(full, me, axis=scatter_dimension))

    def ppermute(x, axis_name, perm, **_kw):
        return jnp.asarray(np.asarray(x))

    def all_to_all(x, axis_name, split_axis, concat_axis, *,
                   axis_index_groups=None, tiled=False, **_kw):
        n = _axis_total(state, axis_name)
        parts = np.split(np.asarray(x), n, axis=split_axis)
        if tiled:
            return jnp.asarray(np.concatenate(parts, axis=concat_axis))
        return jnp.asarray(np.stack(
            [np.take(p, 0, axis=split_axis) for p in parts],
            axis=concat_axis))

    def axis_index(axis_name):
        return jnp.int32(_axis_flat_index(state, axis_name))

    def axis_size(axis_name):
        return _axis_total(state, axis_name)

    def fori_loop(lower, upper, body_fun, init_val, **_kw):
        carry = init_val
        for i in range(_as_int(lower), _as_int(upper)):
            carry = body_fun(jnp.int32(i), carry)
        return carry

    def cond(pred, true_fun, false_fun, *operands, **_kw):
        return true_fun(*operands) if bool(np.asarray(pred)) \
            else false_fun(*operands)

    return dict(all_gather=all_gather, psum=psum, psum_scatter=psum_scatter,
                ppermute=ppermute, all_to_all=all_to_all,
                axis_index=axis_index, axis_size=axis_size,
                fori_loop=fori_loop, cond=cond)


def _fake_when(condition):
    concrete = bool(np.asarray(condition))

    def decorator(f):
        if concrete:
            f()
        return None

    return decorator


@contextlib.contextmanager
def patched(state: CaptureState):
    """Monkeypatch the pl/pltpu/lax surface the kernels touch. Everything is
    restored on exit, including the env knobs the capture pins."""
    from jax import lax as lax_mod
    from jax.experimental import pallas as pl_mod
    from jax.experimental.pallas import tpu as pltpu_mod

    saves: List[Tuple[Any, str, Any]] = []
    _MISSING = object()

    def patch(mod, attr, val):
        # some attrs (e.g. sync_copy) are absent on older jax — the repo's
        # kernels still call them, so install the fake and delete on exit
        saves.append((mod, attr, getattr(mod, attr, _MISSING)))
        setattr(mod, attr, val)

    def tracer():
        return state.require()

    # pallas core
    patch(pl_mod, "pallas_call", _fake_pallas_call(state))
    patch(pl_mod, "when", _fake_when)
    patch(pl_mod, "program_id",
          lambda axis: jnp.int32(tracer().call_stack[-1].grid_pos[axis]))
    patch(pl_mod, "num_programs",
          lambda axis: int(tracer().call_stack[-1].grid_dims[axis]))
    if hasattr(pl_mod, "semaphore_read"):
        patch(pl_mod, "semaphore_read", lambda sem: tracer().signal_read(sem))

    # pallas tpu
    patch(pltpu_mod, "make_async_copy",
          lambda src_ref, dst_ref, sem: FakeCopy(tracer(), src_ref, dst_ref,
                                                 sem))

    def make_async_remote_copy(*, src_ref, dst_ref, send_sem, recv_sem,
                               device_id, device_id_type=None):
        return _PendingRemoteCopy(tracer(), src_ref, dst_ref, send_sem,
                                  recv_sem, device_id)

    patch(pltpu_mod, "make_async_remote_copy", make_async_remote_copy)

    def sync_copy(src_ref, dst_ref):
        t = tracer()
        t.record_read(src_ref)
        t.record_write(dst_ref)
        if src_ref is not dst_ref:
            dst_ref.info.array[dst_ref._np_index()] = (
                src_ref.info.array[src_ref._np_index()].reshape(
                    dst_ref.shape))

    patch(pltpu_mod, "sync_copy", sync_copy)

    def emit_pipeline(body=None, *, grid=None, in_specs=None, out_specs=None,
                      **_kw):
        # Compute pipelines carry no signal protocol in this repo; model one
        # as whole-ref reads of its inputs and writes of its outputs.
        n_in = len(in_specs) if in_specs is not None else 0

        def run(*refs, **_rkw):
            t = tracer()
            for r in refs[:n_in]:
                t.record_read(r)
            for r in refs[n_in:]:
                t.record_write(r)

        return run

    patch(pltpu_mod, "emit_pipeline", emit_pipeline)

    def get_barrier_semaphore():
        t = tracer()
        cid = t.call_stack[-1].collective_id if t.call_stack else None
        return t.barrier_sem(cid)

    patch(pltpu_mod, "get_barrier_semaphore", get_barrier_semaphore)

    def semaphore_signal(sem, inc=1, *, device_id=None, device_id_type=None,
                         **_kw):
        tracer().signal_op(sem, inc, device_id)

    patch(pltpu_mod, "semaphore_signal", semaphore_signal)
    patch(pltpu_mod, "semaphore_wait",
          lambda sem, value=1: tracer().signal_wait_until(sem, value))

    # host-level collectives + control flow
    for attr, val in _fake_collectives(state).items():
        patch(lax_mod, attr, val)

    # jit must not trace the fake driver: capture replays kernels eagerly on
    # numpy buffers, and a jit boundary would turn the assembled outputs into
    # tracers (ops like barrier_all_op wrap their shard_map in jax.jit)
    def fake_jit(fun=None, **_kw):
        if fun is None:
            return lambda f: f
        return fun

    patch(jax, "jit", fake_jit)

    # env: force the compiled protocol path, silence debug perturbations
    env_saves = {}
    for k, v in (("TDT_FORCE_COMPILED", "1"), ("TDT_NOISE", None),
                 ("TDT_SERIAL", None), ("TDT_DETECT_RACES", None)):
        env_saves[k] = os.environ.get(k)
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    try:
        yield
    finally:
        for mod, attr, old in reversed(saves):
            if old is _MISSING:
                delattr(mod, attr)
            else:
                setattr(mod, attr, old)
        for k, old in env_saves.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


# -- fake context ------------------------------------------------------------

def _spec_names(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


class FakeContext:
    """Duck-typed stand-in for :class:`triton_dist_tpu.shmem.ShmemContext`
    whose ``shard_map`` is a sequential per-rank replay driver."""

    def __init__(self, mesh_shape: Dict[str, int] | Sequence[Tuple[str, int]]):
        axes = tuple(mesh_shape.items()) if isinstance(mesh_shape, dict) \
            else tuple(mesh_shape)
        self.state = CaptureState(axes)

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(name for name, _ in self.state.axes)

    @property
    def num_ranks(self) -> int:
        return self.state.n

    def axis_size(self, axis=None) -> int:
        if axis is None:
            return self.num_ranks
        if not isinstance(axis, str):
            return _prod(self.state.sizes[a] for a in axis)
        return self.state.sizes[axis]

    def is_dcn_axis(self, axis: str) -> bool:
        return False

    def create_symm_tensor(self, local_shape, dtype, axis=None):
        n = self.axis_size(axis)
        return jnp.zeros((n, *local_shape), dtype)

    def shard(self, x, spec):
        return x

    # ---- the per-rank replay driver

    def _shard_index(self, coords: Dict[str, int], names) -> Tuple[int, int]:
        idx = 0
        n = 1
        for a in names:
            idx = idx * self.state.sizes[a] + coords[a]
            n *= self.state.sizes[a]
        return idx, n

    def _slice_arg(self, x, spec, coords):
        if spec is None or not hasattr(x, "shape"):
            return x
        arr = np.asarray(x)
        index = [slice(None)] * arr.ndim
        for d, entry in enumerate(tuple(spec)):
            names = _spec_names(entry)
            if not names:
                continue
            idx, n = self._shard_index(coords, names)
            seg = arr.shape[d] // n
            index[d] = slice(idx * seg, (idx + 1) * seg)
        return jnp.asarray(arr[tuple(index)])

    def _assemble(self, shards, spec):
        arr0 = np.asarray(shards[0])
        if spec is None:
            return jnp.asarray(arr0)
        shape = list(arr0.shape)
        dims = []
        for d, entry in enumerate(tuple(spec)):
            names = _spec_names(entry)
            if not names:
                continue
            _, n = self._shard_index(self.state.unflatten(0), names)
            shape[d] *= n
            dims.append((d, names))
        full = np.zeros(tuple(shape), arr0.dtype)
        for flat, shard in enumerate(shards):
            coords = self.state.unflatten(flat)
            index = [slice(None)] * len(shape)
            for d, names in dims:
                idx, n = self._shard_index(coords, names)
                seg = shape[d] // n
                index[d] = slice(idx * seg, (idx + 1) * seg)
            full[tuple(index)] = np.asarray(shard)
        return jnp.asarray(full)

    def shard_map(self, f: Callable[..., Any], in_specs, out_specs,
                  axis_names=None):
        def runner(*args):
            if not isinstance(in_specs, (list, tuple)) or isinstance(
                    in_specs, P):
                specs = (in_specs,) * len(args)
            else:
                specs = tuple(in_specs)
            per_rank = []
            for flat in range(self.state.n):
                coords = self.state.unflatten(flat)
                with self.state.rank(coords):
                    shard_args = [self._slice_arg(a, s, coords)
                                  for a, s in zip(args, specs)]
                    per_rank.append(f(*shard_args))
            out0 = per_rank[0]
            if isinstance(out0, (list, tuple)):
                ospecs = out_specs if isinstance(out_specs, (list, tuple)) \
                    and not isinstance(out_specs, P) \
                    else (out_specs,) * len(out0)
                return tuple(
                    self._assemble([r[i] for r in per_rank], s)
                    for i, s in enumerate(ospecs))
            return self._assemble(per_rank, out_specs)

        return runner


# -- top-level capture -------------------------------------------------------

def capture_op(run: Callable[[FakeContext], Any],
               mesh_shape: Dict[str, int] | Sequence[Tuple[str, int]],
               ) -> Dict[int, List[Event]]:
    """Replay ``run(ctx)`` under a fake mesh of ``mesh_shape`` and return the
    recorded per-rank event streams ({flat_rank: [Event, ...]})."""
    ctx = FakeContext(mesh_shape)
    with patched(ctx.state):
        run(ctx)
    return ctx.state.streams()
