"""Full-participation meshes: every visible device in one PE group.

The Pallas TPU interpreter deadlocks when every device thread blocks in a
semaphore wait simultaneously (the CPU client's execution pool is sized by
device count, so an all-device collective with enough in-kernel work
starves the progress machinery — reproduced at 8-of-8 with a [512,512]
ag_gemm; same shape at 8-of-12 runs in 4 s). ``initialize_distributed``
now works around it by transparently provisioning spare virtual CPU
devices whenever a mesh spans ALL visible CPU devices (context.py) — so a
user's 8-of-8 mesh, and the driver's ``dryrun_multichip`` overlap-op gate,
just work. These tests pin that behavior: they build a mesh over every
visible device and run barrier + collectives through it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import all_gather, reduce_scatter
from triton_dist_tpu.ops.common import barrier_all_op
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx_full():
    n = len(jax.devices())
    return initialize_distributed(axis_names=("x",), mesh_shape=(n,))


def test_barrier_all_full_mesh(ctx_full):
    f = barrier_all_op(ctx_full)
    for _ in range(3):
        out = f()
        out.block_until_ready()
    assert int(np.asarray(out)[0]) == 1


@pytest.mark.parametrize("method", ["push", "ring"])
def test_all_gather_full_mesh(ctx_full, method):
    n = ctx_full.num_ranks
    x = jax.random.normal(jax.random.key(0), (n * 8, 128), jnp.float32)
    xs = ctx_full.shard(x, P("x"))
    y = jax.jit(lambda v: all_gather(ctx_full, v, axis="x", method=method))(xs)
    assert_allclose(np.asarray(y), np.asarray(x))


def test_reduce_scatter_full_mesh(ctx_full):
    n = ctx_full.num_ranks
    x = jnp.round(jax.random.normal(jax.random.key(1), (n * n, 128)) * 4)
    xs = ctx_full.shard(x.astype(jnp.float32), P("x"))
    got = jax.jit(lambda v: reduce_scatter(ctx_full, v, axis="x"))(xs)
    gold = jax.jit(ctx_full.shard_map(
        lambda s: jax.lax.psum_scatter(s, "x", scatter_dimension=0,
                                       tiled=True),
        in_specs=P("x"), out_specs=P("x")))(xs)
    assert_allclose(np.asarray(got), np.asarray(gold))
