"""KV page migration: the disaggregated-serving producer/consumer kernel
(ISSUE 6 tentpole) — a prefill worker pushes one chunk's worth of finished
KV pages into a decode worker's page pool over the one-sided shmem layer.

This is the paper's core protocol applied at the serving tier (PAPER.md
§0; ROADMAP item 2): the producer moves data with one-sided puts and sets
a per-segment signal; the consumer waits on exactly the signals covering
what it will read — no barrier between chunks, no host round-trip in the
wait path. Per chunk:

- **producer** (prefill role): for each finalized page, one
  ``putmem_nbi`` per (layer, page) of k and of v into the consumer's
  symmetric pool at the RESERVED destination ids (the decode-side pages
  the host allocator handed out at admission — "remote reservation"),
  then ``signal_op(+n_pages)`` on the consumer's chunk semaphore: one
  counted arrival per page pushed.
- **consumer** (decode role): waits the chunk signal up to ``n_pages``,
  then waits each page's DMA delivery semaphore (``wait_recv`` — the
  TPU-native "putmem_signal" delivery guarantee, see shmem/device.py) —
  exactly the signals covering the pages this chunk delivers, nothing
  else. Only after those waits does it report the landed count, which is
  the HOST ledger's sole source of truth for signal-gated admission
  (serving/disagg.py): a page whose count never lands is never exposed
  through a block table.

The page ids ride in SMEM as runtime scalars, so ONE compiled program
serves every chunk of every request (the serving compile-guard relies on
this); the static shape is only (pages-per-chunk max, layers, page).

Entry barrier (compiled path): like ``_ag_push_kernel``, the DMA and
chunk semaphores are physical registers reused across calls — the barrier
keeps a fast producer's call k+1 signals out of a consumer still draining
call k. Chunk-to-chunk overlap therefore happens at the SERVING level
(the next chunk's compute overlaps this chunk's migration only on real
async hardware); within a call, all (layer, page) puts are in flight at
once and are quieted in a second pass.

Interpret-mode path (the CPU cluster simulator): jax 0.4.x's generic
Pallas interpreter emulates a remote DMA with an ``all_gather`` inside
the discharge rule — which means every device must execute every
``dma_start`` (SPMD-uniform, single named axis), and REGULAR-semaphore
remote signals are unimplemented (``barrier_all`` included; the
collective kernels' CPU failures in the seed tier-1 set are exactly
this). So under interpret the kernel takes a symmetric variant of the
same protocol: the consumer mirrors each put with a same-shape put into
the PRODUCER's scratch page (keeping the emulation uniform; scratch is
write-only garbage by contract), the chunk announcement is elided, and
delivery ordering rides the per-page DMA semaphores alone — which is the
TPU-native signal anyway; the landed report stays ordered after every
delivery wait, so the host-visible contract is identical on both paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


def _transport_kernel(axis, mesh_axes, producer, consumer, n_layers,
                      interpreting,
                      n_ref, src_ref, dst_ref, tag_ref, kpool, vpool,
                      kpool_out, vpool_out, landed_ref,
                      send_k, recv_k, send_v, recv_v, chunk_sem):
    """Both roles run this SPMD; ``producer``/``consumer`` are role indices
    along ``axis``. Pools are the [L*P, Hkv, ps, D] page-flattened local
    shards of the symmetric pool (aliased through as outputs).

    ``tag_ref`` is the send's attempt/generation tag (ISSUE 7): the
    landed report echoes it next to the count, so the host ledger can
    tell a report from THIS attempt apart from a delayed one belonging
    to an earlier attempt of the same chunk — retry re-sends bump the
    tag, and stale reports are discarded instead of double-counted. The
    echo is grounded here, in the same report that is ordered after the
    delivery waits, not in host bookkeeping.

    All pool traffic goes through the OUTPUT refs: on hardware the alias
    makes them the same buffer, and the generic interpreter only carries
    writes made through the output ref (aliased-input writes are dropped
    — jax b/370563936)."""
    del kpool, vpool                  # aliased: use the output refs only
    kpool, vpool = kpool_out, vpool_out
    me = shd.my_pe(axis)
    pages = kpool.shape[0] // n_layers
    pmax = src_ref.shape[0]
    n = n_ref[0]
    landed_ref[0, 0] = 0
    landed_ref[0, 1] = tag_ref[0]

    if interpreting:
        # -- symmetric interpret path (module docstring) ------------------
        is_prod = me == producer
        peer = shd.pe_at(mesh_axes, axis,
                         jnp.where(is_prod, consumer, producer))
        for i in range(pmax):
            @pl.when(i < n)
            def _(i=i):
                # producer sends real pages; the consumer mirrors into the
                # peer's scratch page (id 0 — reserved, write-only)
                s = jnp.where(is_prod, src_ref[i], 0)
                d = jnp.where(is_prod, dst_ref[i], 0)
                for l in range(n_layers):
                    shd.putmem_nbi(kpool.at[l * pages + d],
                                   kpool.at[l * pages + s],
                                   send_k.at[l, i], recv_k.at[l, i], peer)
                    shd.putmem_nbi(vpool.at[l * pages + d],
                                   vpool.at[l * pages + s],
                                   send_v.at[l, i], recv_v.at[l, i], peer)
        for i in range(pmax):
            @pl.when(i < n)
            def _(i=i):
                my_out = jnp.where(is_prod, src_ref[i], 0)   # what I sent
                my_in = jnp.where(is_prod, 0, dst_ref[i])    # what I got
                for l in range(n_layers):
                    if not shd._serial():   # serialized puts already sent
                        pltpu.make_async_copy(kpool.at[l * pages + my_out],
                                              kpool.at[l * pages + my_out],
                                              send_k.at[l, i]).wait()
                        pltpu.make_async_copy(vpool.at[l * pages + my_out],
                                              vpool.at[l * pages + my_out],
                                              send_v.at[l, i]).wait()
                    shd.wait_recv(kpool.at[l * pages + my_in],
                                  recv_k.at[l, i])
                    shd.wait_recv(vpool.at[l * pages + my_in],
                                  recv_v.at[l, i])
        # ordered after every delivery wait — the consumer-side read of
        # this count is the admission gate's ground truth
        landed_ref[0, 0] = n
        return

    # -- compiled path: the full one-sided protocol -----------------------
    # entry barrier: the semaphores are physical registers reused across
    # calls (see module docstring / _ag_push_kernel)
    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    @pl.when(me == producer)
    def _():
        peer = shd.pe_at(mesh_axes, axis, consumer)
        for i in range(pmax):
            @pl.when(i < n)
            def _(i=i):
                s, d = src_ref[i], dst_ref[i]
                for l in range(n_layers):
                    shd.putmem_nbi(kpool.at[l * pages + d],
                                   kpool.at[l * pages + s],
                                   send_k.at[l, i], recv_k.at[l, i], peer)
                    shd.putmem_nbi(vpool.at[l * pages + d],
                                   vpool.at[l * pages + s],
                                   send_v.at[l, i], recv_v.at[l, i], peer)
        # the per-chunk signal: one counted arrival per page pushed
        shd.signal_op(chunk_sem, n, pe=peer)
        if not shd._serial():
            # quiet (skip under TDT_SERIAL — sends already completed at
            # source there, a second wait would hang): the descriptors are
            # out of scope, so wait the send semaphores through the
            # standard same-ref-shape trick
            for i in range(pmax):
                @pl.when(i < n)
                def _(i=i):
                    s = src_ref[i]
                    for l in range(n_layers):
                        pltpu.make_async_copy(kpool.at[l * pages + s],
                                              kpool.at[l * pages + s],
                                              send_k.at[l, i]).wait()
                        pltpu.make_async_copy(vpool.at[l * pages + s],
                                              vpool.at[l * pages + s],
                                              send_v.at[l, i]).wait()
        landed_ref[0, 0] = n          # producer-side report: pages pushed

    @pl.when(me == consumer)
    def _():
        # signal-gated consumption: first the chunk announcement (counts
        # must cover every page of the chunk), then each page's delivery
        shd.signal_wait_until(chunk_sem, n)
        for i in range(pmax):
            @pl.when(i < n)
            def _(i=i):
                d = dst_ref[i]
                for l in range(n_layers):
                    shd.wait_recv(kpool.at[l * pages + d], recv_k.at[l, i])
                    shd.wait_recv(vpool.at[l * pages + d], recv_v.at[l, i])
        # ordered after the waits: this count is only ever observed when
        # every covered page has physically landed
        landed_ref[0, 0] = n


def paged_transport(ctx: ShmemContext, pool_k: jax.Array, pool_v: jax.Array,
                    src_ids: jax.Array, dst_ids: jax.Array,
                    n_pages: jax.Array, axis: str | None = None,
                    producer: int = 0, consumer: int = 1,
                    tag: jax.Array | int = 0, name: str = "page_migrate"):
    """The shared per-(layer, page) put + counted-signal transport core
    (ISSUE 17 refactor): ``migrate_pages`` (disagg prefill→decode handoff)
    and ``lend_pages`` (cluster prefix lending) are the SAME wire protocol
    with different role semantics, so both are thin fronts over this one
    host wrapper. ``name`` keys the collective id — distinct fronts get
    distinct collective channels even on the same axis.

    ``pool_k``/``pool_v``: symmetric pools from ``create_symm_tensor`` —
    global ``[n_roles, L, P, Hkv, page_size, D]`` sharded ``P(axis)``
    (each role owns an identically-shaped local pool; remote refs are
    (buffer, device) pairs, symmetric by construction). Page id 0 of each
    local pool must be a reserved scratch page (never a live sequence's —
    the interpret path mirror-writes the producer's).
    ``src_ids``/``dst_ids``: ``[pmax]`` int32, replicated — producer-local
    source page ids and consumer-side destination ids, valid up to
    ``n_pages`` (``[1]`` int32). Entries past ``n_pages`` are never
    dereferenced, so pad with anything in range. ``tag`` is the attempt/
    generation stamp echoed back in the landed report (see
    ``_transport_kernel``; 0 for first sends, bumped per retry).

    Returns ``(pool_k, pool_v, landed [n_roles, 2] int32)`` — pools
    aliased in place, ``landed[consumer] == (count, tag)``: the kernel-
    reported delivered-page count (the signal ledger's ground truth)
    plus the echoed attempt tag. ALL ranks on ``axis`` must enter this
    call (it is one SPMD program, like every collective in ops/); ranks
    outside the ``{producer, consumer}`` pair participate only in the
    entry barrier."""
    axis = axis or ctx.axis_names[0]
    mesh_axes = ctx.axis_names
    interp = default_interpret()

    def f(n, src, dst, tg, kp, vp):
        L = kp.shape[1]
        flat = lambda a: a.reshape((a.shape[1] * a.shape[2],) + a.shape[3:])
        kpl, vpl = flat(kp), flat(vp)
        pmax = src.shape[0]
        kernel = lambda *refs: _transport_kernel(
            axis, mesh_axes, producer, consumer, L,
            interp is not False, *refs)
        ko, vo, landed = pl.pallas_call(
            kernel,
            out_shape=(jax.ShapeDtypeStruct(kpl.shape, kpl.dtype),
                       jax.ShapeDtypeStruct(vpl.shape, vpl.dtype),
                       jax.ShapeDtypeStruct((1, 2), jnp.int32)),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 4
            + [pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pltpu.SMEM)),
            input_output_aliases={4: 0, 5: 1},
            scratch_shapes=[pltpu.SemaphoreType.DMA((L, pmax)),
                            pltpu.SemaphoreType.DMA((L, pmax)),
                            pltpu.SemaphoreType.DMA((L, pmax)),
                            pltpu.SemaphoreType.DMA((L, pmax)),
                            pltpu.SemaphoreType.REGULAR],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"{name}_{axis}")),
            interpret=interp,
        )(n, src, dst, tg, kpl, vpl)
        return ko.reshape(kp.shape), vo.reshape(vp.shape), landed

    sm = ctx.shard_map(f, in_specs=(P(), P(), P(), P(), P(axis), P(axis)),
                       out_specs=(P(axis), P(axis), P(axis, None)))
    return sm(jnp.asarray(n_pages, jnp.int32).reshape(1),
              jnp.asarray(src_ids, jnp.int32),
              jnp.asarray(dst_ids, jnp.int32),
              jnp.asarray(tag, jnp.int32).reshape(1), pool_k, pool_v)


def migrate_pages(ctx: ShmemContext, pool_k: jax.Array, pool_v: jax.Array,
                  src_ids: jax.Array, dst_ids: jax.Array, n_pages: jax.Array,
                  axis: str | None = None, producer: int = 0,
                  consumer: int = 1, tag: jax.Array | int = 0):
    """Collective chunk migration over the role axis — the disaggregated
    prefill→decode handoff front over :func:`paged_transport` (argument
    and return contracts documented there)."""
    return paged_transport(ctx, pool_k, pool_v, src_ids, dst_ids, n_pages,
                           axis=axis, producer=producer, consumer=consumer,
                           tag=tag, name="page_migrate")


__all__ = ["migrate_pages", "paged_transport"]
