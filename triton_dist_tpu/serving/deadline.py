"""Deadlines, bounded exponential backoff, and the engine stall watchdog.

Before ISSUE 7 every host-side blocking wait in the serving tier counted
steps its own way (`_wait_steps` in the disagg engine, nothing at all on
the colocated engine's admission gate), and the single handler —
`migrate_timeout_steps` — killed the whole engine. This module is the one
vocabulary all of those waits now share:

- ``Deadline``: a budget in *engine-step space* — the deterministic clock
  every replayable test runs on — with an optional wall-clock cap as a
  belt-and-braces hang guard for real deployments (wall time is never
  consulted unless explicitly configured, so CI replays stay exact).
- ``Backoff``: a bounded exponential retry schedule. Each expiry asks
  ``next_budget()``; ``None`` means the rungs are exhausted and the
  caller must move down the recovery ladder (degrade, then fail).
- ``EngineStallError``: the typed "the engine as a whole stopped making
  progress" diagnosis raised by ``engine.run``'s watchdog — the backstop
  that turns any residual livelock bug into a loud, described failure
  instead of a hang.
"""

from __future__ import annotations

import time


class EngineStallError(RuntimeError):
    """``engine.run`` made no progress for a full watchdog window.

    Per-request recovery (retry -> degrade -> fail) should consume every
    fault the chaos plans can inject; this error firing means a wait that
    has no deadline, i.e. a bug. The message carries the engine's state
    dump so the report is actionable without a debugger.
    """


class Deadline:
    """A wait budget anchored at creation time.

    ``steps`` is in engine-step space (the deterministic clock); pass the
    current step as ``now``. ``wall_s`` optionally adds a wall-clock cap:
    ``expired()`` then also fires once that much real time has passed,
    whatever the step counter says. Call ``rearm`` to reuse the object
    for the next rung instead of allocating a new one.
    """

    __slots__ = ("expires_step", "_wall_deadline", "_wall_s")

    def __init__(self, steps: int, now: int, wall_s: float | None = None):
        self.expires_step = now + int(steps)
        self._wall_s = wall_s
        self._wall_deadline = (None if wall_s is None
                               else time.perf_counter() + wall_s)

    def rearm(self, steps: int, now: int) -> "Deadline":
        self.expires_step = now + int(steps)
        if self._wall_s is not None:
            self._wall_deadline = time.perf_counter() + self._wall_s
        return self

    def expired(self, now: int) -> bool:
        if now >= self.expires_step:
            return True
        return (self._wall_deadline is not None
                and time.perf_counter() >= self._wall_deadline)

    def remaining(self, now: int) -> int:
        return max(0, self.expires_step - now)


class Backoff:
    """Bounded exponential backoff: budgets ``base, base*factor, ...``
    for up to ``max_retries`` rungs, then ``None`` forever.

    The *attempt* count (how many budgets have been handed out) doubles
    as the ledger generation tag for retried sends.
    """

    __slots__ = ("base", "factor", "max_retries", "attempt")

    def __init__(self, base: int, factor: int = 2, max_retries: int = 3):
        if base < 1:
            raise ValueError(f"backoff base must be >= 1, got {base}")
        self.base = int(base)
        self.factor = int(factor)
        self.max_retries = int(max_retries)
        self.attempt = 0

    def next_budget(self) -> int | None:
        if self.attempt >= self.max_retries:
            return None
        budget = self.base * self.factor ** self.attempt
        self.attempt += 1
        return budget

    @property
    def exhausted(self) -> bool:
        return self.attempt >= self.max_retries


__all__ = ["Deadline", "Backoff", "EngineStallError"]
