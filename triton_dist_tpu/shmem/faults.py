"""Seeded fault injection for the one-sided signal plane (ISSUE 7).

The paper's correctness story is one-sided puts plus counted signal/wait
pairs; everything above it (the migration channel, signal-gated
admission, the serving engines) is correct *when nothing goes wrong*. A
``FaultPlan`` is the adversary: a deterministic, seeded schedule of
signal drops, duplicated increments, delayed deliveries and dead peers
that the signal plane's hooks consult — so the recovery machinery in
``serving/`` can be driven through every failure mode of the protocol
matrix (docs/robustness.md) and every run replays bit-identically from
its seed.

Two consultation tiers, matching where faults physically occur:

- **device tier** (trace-time, like ``TDT_SERIAL``/``TDT_NOISE``): the
  ``shmem.device`` hooks consult the ACTIVE plan while a kernel is being
  traced. ``producer_noise`` widens producer/consumer timing windows by
  ``device_put_delay`` extra self-copy trips, ``signal_op`` can drop or
  duplicate its increment (``device_drop_signals`` /
  ``device_dup_signals``), and ``putmem_nbi`` can swallow the put
  entirely (``device_peer_dead`` — the DMA never leaves the source).
  These are blunt by design: they poison EVERY kernel traced while the
  plan is active, exactly like the serial/noise debug switches, and are
  meant for kernel-level drills and hang bisection (a dropped device
  signal SHOULD hang the consumer — the host-side deadlines are what
  turn that hang into a typed failure).
- **host tier** (per-event): the serving tier's migration channel asks
  the plan one question per chunk-send attempt —
  ``signal_action(rid, chunk_idx, attempt)`` — and one per step —
  ``peer_dead(step)``. Decisions are a pure function of
  ``(seed, kind, rid, chunk_idx, attempt)`` via keyed hashing (no
  sequential RNG state), so a schedule is independent of event arrival
  order and replayable from the seed alone; a retried attempt re-rolls
  its own fate, which is what lets a bounded-retry ladder actually
  recover from a ``p_drop < 1`` plan.

Activation is scoped like the other trace-time debug knobs: pass a plan
to the engine / use the ``use_plan`` context manager for programmatic
scope, or set ``TDT_FAULTS="seed=3,drop=0.2,dup=0.05,delay=0.3,dead=40"``
in the environment (read at consult time).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import zlib

_ACTIVE: "FaultPlan | None" = None
_ENV_CACHE: tuple[str, "FaultPlan | None"] | None = None


class InjectedCrash(RuntimeError):
    """An engine-tier ``crash`` fault fired (ISSUE 9): the serving process
    is presumed dead at this step. Everything host-side is lost except the
    control-plane journal — the recovery harness (tests,
    ``serve_sim.py --recover``) builds a fresh engine and restores it from
    the journal's last checkpoint + suffix replay. Raised from
    ``engine.run`` AFTER the step's journal entries were appended, so the
    WAL semantics are honest: an event is durable iff it was journaled."""


def _uniform(seed: int, *key) -> float:
    """Deterministic uniform in [0, 1) keyed by the event identity.

    crc32 of the printed key — not cryptographic, but stable across
    runs/platforms/python versions (unlike ``hash()``), cheap, and
    independent draws per (kind, rid, chunk, attempt) coordinate."""
    h = zlib.crc32(repr((seed,) + key).encode("utf-8"))
    return (h & 0xFFFFFFFF) / 2.0 ** 32


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One seeded, replayable fault schedule. Frozen: a plan carries no
    mutable state — every decision is recomputable, which is what makes
    "replay the schedule from its seed" a one-liner.

    Host-tier knobs (per chunk-send attempt):

    - ``p_drop``: probability the attempt's signal/landed report is lost
      in flight (the pages may have landed; the announcement did not).
    - ``p_dup``: probability the signal increment is duplicated — the
      over-signal protocol violation ``ChunkSignalLedger`` must detect.
    - ``p_delay`` / ``max_delay_steps``: probability the landed report is
      delivered late, and the (deterministic, per-event) lateness in
      engine steps. A delayed report can arrive after a retry bumped the
      chunk's generation — the ledger discards it as stale.
    - ``dead_peer_after``: engine step from which the transport to the
      peer is dead — puts and signals all vanish (``None`` = never).
    - ``rids``: optionally scope every host-tier fault to these request
      ids (targeted drills); ``None`` faults everything.

    Device-tier knobs (trace-time, see module docstring):
    ``device_put_delay``, ``device_drop_signals``, ``device_dup_signals``,
    ``device_peer_dead``.
    """

    seed: int = 0
    p_drop: float = 0.0
    p_dup: float = 0.0
    p_delay: float = 0.0
    max_delay_steps: int = 8
    dead_peer_after: int | None = None
    rids: tuple[int, ...] | None = None
    # engine tier (ISSUE 9): process crashes and replicated-digest skew.
    # ``crash_at`` kills the engine at exactly these steps — but only in
    # incarnation 0 (the original process), so the restored engine does
    # not re-crash at the same step forever; ``p_crash`` is the keyed-hash
    # probabilistic form, rolled per (step, incarnation) so a restarted
    # engine re-rolls its own fate. ``digest_skew_at`` / ``p_digest_skew``
    # corrupt ONE rank's control digest before the sharded engine's
    # cross-check — ``digest_skew_at`` only on attempt 0 (a transient
    # divergence the restore rung must absorb), ``p_digest_skew`` re-rolls
    # per (step, attempt).
    crash_at: tuple[int, ...] | None = None
    p_crash: float = 0.0
    digest_skew_at: tuple[int, ...] | None = None
    p_digest_skew: float = 0.0
    # device tier (trace-time)
    device_put_delay: int = 0
    device_drop_signals: bool = False
    device_dup_signals: bool = False
    device_peer_dead: bool = False

    # -- host tier ---------------------------------------------------------
    def _scoped(self, rid) -> bool:
        return self.rids is None or rid in self.rids

    def peer_dead(self, step: int) -> bool:
        """Transport to the peer is dead at ``step`` (nothing sent from
        here on arrives — puts, signals, retries alike)."""
        return (self.dead_peer_after is not None
                and step >= self.dead_peer_after)

    def signal_action(self, rid, chunk_idx: int, attempt: int
                      ) -> tuple[str, int]:
        """Fate of one chunk-send attempt's signal:
        ``("ok", 0)``, ``("drop", 0)``, ``("dup", 0)`` or
        ``("delay", k)`` with ``k >= 1`` engine steps of lateness.
        Each attempt re-rolls independently (keyed by ``attempt``), so
        retry CAN succeed where the first send faulted."""
        if not self._scoped(rid):
            return ("ok", 0)
        if _uniform(self.seed, "drop", rid, chunk_idx, attempt) < self.p_drop:
            return ("drop", 0)
        if _uniform(self.seed, "dup", rid, chunk_idx, attempt) < self.p_dup:
            return ("dup", 0)
        if _uniform(self.seed, "delay", rid, chunk_idx,
                    attempt) < self.p_delay:
            k = 1 + int(_uniform(self.seed, "delay_k", rid, chunk_idx,
                                 attempt) * self.max_delay_steps)
            return ("delay", k)
        return ("ok", 0)

    # -- engine tier (ISSUE 9) ---------------------------------------------
    def crash(self, step: int, incarnation: int = 0) -> bool:
        """Should the engine process die at ``step``? ``crash_at`` fires
        only in incarnation 0; ``p_crash`` is keyed by (step, incarnation)
        so every restart re-rolls independently."""
        if (self.crash_at is not None and incarnation == 0
                and step in self.crash_at):
            return True
        return bool(self.p_crash) and _uniform(
            self.seed, "crash", step, incarnation) < self.p_crash

    def digest_skew(self, step: int, attempt: int = 0) -> int:
        """Non-zero word to add to one rank's control digest at ``step``
        (0 = no skew). ``attempt`` counts divergences already recovered at
        this step: the scheduled ``digest_skew_at`` form fires only on
        attempt 0 (transient — the restore rung must absorb it), the
        probabilistic form re-rolls per attempt."""
        if (self.digest_skew_at is not None and attempt == 0
                and step in self.digest_skew_at):
            return 1 + int(_uniform(self.seed, "skew_v", step) * 0xFFFF)
        if self.p_digest_skew and _uniform(
                self.seed, "digest_skew", step, attempt) < self.p_digest_skew:
            return 1 + int(_uniform(self.seed, "skew_v", step, attempt)
                           * 0xFFFF)
        return 0

    def skew_rank(self, step: int, n_ranks: int) -> int:
        """Which rank's digest the skew lands on (keyed, deterministic)."""
        return int(_uniform(self.seed, "skew_rank", step) * n_ranks)

    # -- device tier -------------------------------------------------------
    def device_signal_inc(self, inc):
        """What ``signal_op`` should emit under this plan: ``None`` to
        drop the signal entirely, a doubled increment for a duplicate,
        or ``inc`` unchanged."""
        if self.device_drop_signals:
            return None
        if self.device_dup_signals:
            return inc * 2
        return inc

    # -- bookkeeping -------------------------------------------------------
    @property
    def any_host_faults(self) -> bool:
        return (self.p_drop > 0 or self.p_dup > 0 or self.p_delay > 0
                or self.dead_peer_after is not None)

    @property
    def any_engine_faults(self) -> bool:
        return (self.crash_at is not None or self.p_crash > 0
                or self.digest_skew_at is not None or self.p_digest_skew > 0)

    def describe(self) -> str:
        on = [f"seed={self.seed}"]
        for k in ("p_drop", "p_dup", "p_delay", "p_crash", "p_digest_skew"):
            v = getattr(self, k)
            if v:
                on.append(f"{k}={v}")
        if self.dead_peer_after is not None:
            on.append(f"dead_peer_after={self.dead_peer_after}")
        if self.crash_at is not None:
            on.append(f"crash_at={list(self.crash_at)}")
        if self.digest_skew_at is not None:
            on.append(f"digest_skew_at={list(self.digest_skew_at)}")
        if self.rids is not None:
            on.append(f"rids={list(self.rids)}")
        for k in ("device_put_delay", "device_drop_signals",
                  "device_dup_signals", "device_peer_dead"):
            v = getattr(self, k)
            if v:
                on.append(f"{k}={v}")
        return "FaultPlan(" + ", ".join(on) + ")"

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the compact env/CLI form: either a bare integer seed
        (default probabilities: drop 0.15, delay 0.25) or a
        comma-separated ``k=v`` list — ``seed=3,drop=0.2,dup=0.05,``
        ``delay=0.3,delay_max=6,dead=40,rids=1|4|7``."""
        spec = spec.strip()
        if not spec:
            raise ValueError("empty fault spec")
        try:
            return cls(seed=int(spec), p_drop=0.15, p_delay=0.25)
        except ValueError:
            pass
        keys = {"seed": ("seed", int), "drop": ("p_drop", float),
                "dup": ("p_dup", float), "delay": ("p_delay", float),
                "delay_max": ("max_delay_steps", int),
                "dead": ("dead_peer_after", int),
                "crash": ("p_crash", float),
                "skew": ("p_digest_skew", float),
                "put_delay": ("device_put_delay", int)}
        kw = {}
        for part in spec.split(","):
            k, _, v = part.partition("=")
            k = k.strip()
            if k == "rids":
                kw["rids"] = tuple(int(r) for r in v.split("|"))
            elif k == "crash_at":
                kw["crash_at"] = tuple(int(s) for s in v.split("|"))
            elif k == "skew_at":
                kw["digest_skew_at"] = tuple(int(s) for s in v.split("|"))
            elif k in keys:
                name, cast = keys[k]
                kw[name] = cast(v)
            else:
                raise ValueError(f"unknown fault-spec key {k!r} in {spec!r}")
        return cls(**kw)


# -- activation scoping ------------------------------------------------------

def activate(plan: FaultPlan | None) -> FaultPlan | None:
    """Install ``plan`` as the process-wide active plan (``None`` clears).
    Returns the previous plan so callers can restore it."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, plan
    return prev


@contextlib.contextmanager
def use_plan(plan: FaultPlan):
    """Scope a plan to a ``with`` block (the programmatic twin of the
    ``TDT_FAULTS`` env knob)."""
    prev = activate(plan)
    try:
        yield plan
    finally:
        activate(prev)


def active_plan() -> FaultPlan | None:
    """The plan the hooks should consult right now: the programmatically
    activated one, else one parsed from ``TDT_FAULTS`` (cached per env
    value — consulted at trace time like ``TDT_SERIAL``), else None."""
    if _ACTIVE is not None:
        return _ACTIVE
    global _ENV_CACHE
    spec = os.environ.get("TDT_FAULTS")
    if not spec:
        return None
    if _ENV_CACHE is None or _ENV_CACHE[0] != spec:
        _ENV_CACHE = (spec, FaultPlan.from_spec(spec))
    return _ENV_CACHE[1]


__all__ = ["FaultPlan", "InjectedCrash", "activate", "use_plan",
           "active_plan"]
