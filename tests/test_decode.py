"""Decode/serving-path tests: paged attention kernel vs dense golden
(parity: reference ref_paged_attn, test_sp_decode_attn.py:81-134) and the
prefill→decode_step→generate loop vs the full forward."""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import (LlamaConfig, decode_step, forward,
                                          generate, init_kv_cache,
                                          init_params, prefill)
from triton_dist_tpu.ops.flash_decode import gqa_decode_paged


def _ref_paged_attn(q, k_pages, v_pages, block_table, kv_len):
    """Dense golden: gather pages into a contiguous cache, plain softmax
    attention (mirrors the reference's ref_paged_attn)."""
    B, Hq, D = q.shape
    _, Hkv, ps, _ = k_pages.shape
    G = Hq // Hkv
    outs = []
    for b in range(B):
        k = np.concatenate([np.asarray(k_pages[p]) for p in
                            np.asarray(block_table[b])], axis=1)  # [Hkv,S,D]
        v = np.concatenate([np.asarray(v_pages[p]) for p in
                            np.asarray(block_table[b])], axis=1)
        L = int(kv_len[b])
        k, v = k[:, :L].astype(np.float32), v[:, :L].astype(np.float32)
        qb = np.asarray(q[b]).astype(np.float32).reshape(Hkv, G, D)
        s = np.einsum("hgd,htd->hgt", qb, k) / math.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        o = np.einsum("hgt,htd->hgd", p, v).reshape(Hq, D)
        outs.append(o)
    return np.stack(outs)


def test_paged_decode_matches_dense():
    B, Hq, Hkv, D, ps, pages_per_seq = 2, 4, 2, 64, 16, 4
    pool = B * pages_per_seq
    key = jax.random.key(0)
    q = jax.random.normal(key, (B, Hq, D), jnp.float32)
    k_pages = jax.random.normal(jax.random.key(1), (pool, Hkv, ps, D),
                                jnp.float32)
    v_pages = jax.random.normal(jax.random.key(2), (pool, Hkv, ps, D),
                                jnp.float32)
    # non-trivial page assignment + ragged lengths
    bt = jnp.asarray(np.random.default_rng(0).permutation(pool)
                     .reshape(B, pages_per_seq).astype(np.int32))
    kv_len = jnp.asarray([3 * ps + 5, 2 * ps], jnp.int32)
    out, lse = jax.jit(gqa_decode_paged)(q, k_pages, v_pages, bt, kv_len)
    ref = _ref_paged_attn(q, k_pages, v_pages, bt, kv_len)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=2e-5)
    assert np.all(np.isfinite(np.asarray(lse[:, :, 0])))


def test_decode_step_matches_forward():
    """Incremental decode logits must match the full-sequence forward at
    every position (KV-cache correctness)."""
    cfg = dataclasses.replace(LlamaConfig.tiny(n_layers=2),
                              dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    B, S = 2, 8
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
    full = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)

    cache = init_kv_cache(cfg, B, 16)
    logits_p, cache = jax.jit(
        lambda p, t, c: prefill(p, t, cfg, c))(params, tokens[:, :4], cache)
    np.testing.assert_allclose(np.asarray(logits_p), np.asarray(full[:, 3]),
                               atol=2e-3, rtol=2e-3)
    step = jax.jit(lambda p, t, pos, c: decode_step(p, t, pos, cfg, c))
    for i in range(4, S):
        logits_d, cache = step(params, tokens[:, i], i, cache)
        np.testing.assert_allclose(np.asarray(logits_d),
                                   np.asarray(full[:, i]),
                                   atol=2e-3, rtol=2e-3)


def test_generate_greedy_consistent():
    """generate()'s first emitted token equals the forward argmax."""
    cfg = dataclasses.replace(LlamaConfig.tiny(n_layers=2),
                              dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, cfg.vocab_size)
    toks = jax.jit(lambda p, t: generate(p, t, cfg, max_new_tokens=3,
                                         max_seq=16))(params, prompt)
    assert toks.shape == (2, 3)
    full = forward(params, prompt, cfg)
    np.testing.assert_array_equal(np.asarray(toks[:, 0]),
                                  np.asarray(jnp.argmax(full[:, -1], -1)))


@pytest.mark.quick
def test_sp_decode_step_matches_single():
    """decode_step_sp over a 4-way KV-sharded cache == single-device
    decode_step (the model-level SP serving loop; reference
    sp_flash_decode_layer.py:78-184)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from conftest import TEST_WORLD
    from triton_dist_tpu.models.llama import decode_step_sp
    from triton_dist_tpu.shmem.context import initialize_distributed

    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))
    cfg = LlamaConfig(vocab_size=256, d_model=256, n_layers=2, n_heads=2,
                      n_kv_heads=2, d_ff=256, max_seq_len=4 * 32)
    params = init_params(jax.random.key(0), cfg)
    B, S = 4, cfg.max_seq_len  # B*Hq = 8 rows (sublane-safe merge buffer)
    cache = init_kv_cache(cfg, B, S)
    spec = P(None, None, None, "x", None)
    cache = {k: jax.device_put(v, NamedSharding(ctx.mesh, spec))
             for k, v in cache.items()}

    token = jax.random.randint(jax.random.key(1), (B,), 0, cfg.vocab_size)
    logits_ref = None
    pos = 0
    # a few steps so later steps read cache entries written by earlier ones
    step_sp = jax.jit(lambda p, t, pos, c: decode_step_sp(
        ctx, p, t, pos, cfg, c, axis="x"))
    step_1d = jax.jit(lambda p, t, pos, c: decode_step(p, t, pos, cfg, c))
    cache_1d = init_kv_cache(cfg, B, S)
    for pos in range(3):
        l_sp, cache = step_sp(params, token, pos, cache)
        l_1d, cache_1d = step_1d(params, token, pos, cache_1d)
        # bf16 activations + a different partial-merge order: ~5e-3 noise
        np.testing.assert_allclose(np.asarray(l_sp), np.asarray(l_1d),
                                   rtol=1e-2, atol=1e-2)
        # host round-trip: a mesh-sharded token input would drag the SPMD
        # partitioner into the single-device path's scanned interpret kernel
        token = jnp.asarray(np.argmax(np.asarray(l_sp), axis=-1),
                            jnp.int32)


def test_moe_sp_decode_step_matches_dense():
    """moe_decode_step_sp (SP flash-decode attention + EP A2A MoE FFN in
    one jitted step — the DeepSeek-style serving composition) == a
    single-device dense reference step, over several steps so the cache
    round-trips."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from conftest import TEST_WORLD
    from triton_dist_tpu.layers.ep_a2a_layer import EPAll2AllLayer
    from triton_dist_tpu.models.moe import (MoEConfig, init_moe_params,
                                            moe_decode_step_sp)
    from triton_dist_tpu.shmem.context import initialize_distributed

    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))
    n = ctx.num_ranks
    base = LlamaConfig(vocab_size=256, d_model=128, n_layers=2, n_heads=2,
                       n_kv_heads=2, d_ff=128, max_seq_len=4 * 32)
    cfg = MoEConfig(base=base, num_experts=2 * n, topk=2, moe_d_ff=128)
    params = init_moe_params(jax.random.key(0), cfg)
    B, S = 4, base.max_seq_len
    layer = EPAll2AllLayer.create(ctx, max_tokens=B // n, hidden=base.d_model,
                                  topk=cfg.topk, num_experts=cfg.num_experts,
                                  axis="x", dtype=base.dtype)

    cache = init_kv_cache(base, B, S)
    spec = P(None, None, None, "x", None)
    cache = {k: jax.device_put(v, NamedSharding(ctx.mesh, spec))
             for k, v in cache.items()}
    cache_1d = init_kv_cache(base, B, S)

    def dense_moe_ffn(h, p):
        """Dense per-expert golden FFN — plugged into decode_step's ffn
        hook so the attention/cache plumbing is the shared one."""
        h32 = h.astype(jnp.float32)
        gv, gi = jax.lax.top_k(
            jax.nn.softmax(h32 @ p["w_router"], -1), cfg.topk)
        gv = gv / jnp.sum(gv, -1, keepdims=True)
        act = jax.nn.silu(jnp.einsum("td,edf->tef", h32,
                                     p["we_gate"].astype(jnp.float32))) \
            * jnp.einsum("td,edf->tef", h32,
                         p["we_up"].astype(jnp.float32))
        ye = jnp.einsum("tef,efd->ted",
                        act.astype(cfg.base.dtype).astype(jnp.float32),
                        p["we_down"].astype(jnp.float32))
        sel = jnp.take_along_axis(ye, gi[..., None], axis=1)
        return jnp.sum(sel * gv[..., None], axis=1)

    def dense_step(params, token, pos, cache):
        return decode_step(params, token, pos, cfg.base, cache,
                           ffn=dense_moe_ffn)

    step_sp = jax.jit(lambda p, t, pos, c: moe_decode_step_sp(
        ctx, layer, p, t, pos, cfg, c, sp_axis="x"))
    step_1d = jax.jit(dense_step)

    token = jax.random.randint(jax.random.key(1), (B,), 0, base.vocab_size)
    for pos in range(3):
        l_sp, cache = step_sp(params, token, pos, cache)
        l_1d, cache_1d = step_1d(params, token, pos, cache_1d)
        np.testing.assert_allclose(np.asarray(l_sp), np.asarray(l_1d),
                                   rtol=3e-2, atol=3e-2)
        token = jnp.asarray(np.argmax(np.asarray(l_sp), axis=-1), jnp.int32)
