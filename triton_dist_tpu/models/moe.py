"""MoE model family (Mixtral/DeepSeek-style) over the EP kernel stack.

The reference's MoE story is kernel-level: EP All-to-All dispatch/combine
(reference python/triton_dist/kernels/nvidia/low_latency_all_to_all.py,
ep_a2a.py) and MoE-TP grouped-GEMM overlap ops (allgather_group_gemm.py,
moe_reduce_rs.py), exercised end-to-end by test_ep_moe_inference.py (an MoE
block: router → dispatch → grouped FFN → combine). This module provides that
same end-to-end MoE block as part of a full model, two ways:

- ``moe_mlp_gshard``: differentiable GShard-style einsum dispatch with
  experts sharded over an ``ep`` mesh axis — the *training* path. XLA turns
  the dispatch/combine einsums into all-to-alls over ICI and overlaps them
  with the expert GEMMs (async collectives); grads flow through everything.
- ``moe_mlp_ep_overlap``: the *inference* path through the hand-overlapped
  Pallas A2A dispatch/combine + grouped-GEMM kernels (the reference's
  showcase pipeline, low_latency_all_to_all.py:189-270 + ep_a2a_layer.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.models.llama import (LlamaConfig, rmsnorm, rope,
                                          _attention)
from triton_dist_tpu.shmem.context import ShmemContext


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    base: LlamaConfig = dataclasses.field(default_factory=LlamaConfig)
    num_experts: int = 8
    topk: int = 2
    moe_d_ff: int = 2048           # per-expert FFN width
    capacity_factor: float = 1.25  # train-path expert capacity
    router_aux_coef: float = 0.01  # load-balance loss weight

    @classmethod
    def tiny(cls, n_layers: int = 2, num_experts: int = 4):
        return cls(base=LlamaConfig.tiny(n_layers), num_experts=num_experts,
                   topk=2, moe_d_ff=128)

    @classmethod
    def mixtral_8x7b(cls):
        return cls(base=LlamaConfig(vocab_size=32000, d_model=4096,
                                    n_layers=32, n_heads=32, n_kv_heads=8,
                                    d_ff=14336),
                   num_experts=8, topk=2, moe_d_ff=14336)

    @classmethod
    def deepseek_infer(cls):
        """The reference's A2A benchmark shape: hidden 7168, topk 8
        (BASELINE.md / reference README.md:55)."""
        return cls(base=LlamaConfig(vocab_size=129280, d_model=7168,
                                    n_layers=4, n_heads=56, n_kv_heads=8,
                                    d_ff=18432),
                   num_experts=64, topk=8, moe_d_ff=2048)


def init_moe_params(key: jax.Array, cfg: MoEConfig) -> dict:
    """Llama-style attention params + per-layer MoE FFN params (router +
    stacked expert weights)."""
    from triton_dist_tpu.models.llama import init_params
    b = cfg.base
    L, D, F, E = b.n_layers, b.d_model, cfg.moe_d_ff, cfg.num_experts
    params = init_params(key, b)
    blocks = dict(params["blocks"])
    for k in ("w_gate", "w_up", "w_down"):
        del blocks[k]
    keys = jax.random.split(jax.random.fold_in(key, 1), 4)
    s = 0.02

    def norm(k, *shape):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(b.dtype)

    blocks["w_router"] = jnp.asarray(
        jax.random.normal(keys[0], (L, D, E), jnp.float32) * s)
    blocks["we_gate"] = norm(keys[1], L, E, D, F)
    blocks["we_up"] = norm(keys[2], L, E, D, F)
    blocks["we_down"] = norm(keys[3], L, E, F, D)
    params["blocks"] = blocks
    return params


def moe_param_specs(cfg: MoEConfig, tp: str | None = "tp",
                    ep: str | None = "ep", pp: str | None = None) -> dict:
    """Specs tree matching ``init_moe_params``: experts sharded over ``ep``,
    attention Megatron-TP over ``tp``."""
    from triton_dist_tpu.models.llama import param_specs
    specs = param_specs(cfg.base, tp=tp, pp=pp)
    blocks = dict(specs["blocks"])
    for k in ("w_gate", "w_up", "w_down"):
        del blocks[k]
    blocks["w_router"] = P(pp, None, None)
    blocks["we_gate"] = P(pp, ep, None, tp)
    blocks["we_up"] = P(pp, ep, None, tp)
    blocks["we_down"] = P(pp, ep, tp, None)
    specs["blocks"] = blocks
    return specs


# ---------------------------------------------------------------------------
# training path: GShard-style differentiable dispatch (ep via GSPMD)
# ---------------------------------------------------------------------------

def moe_mlp_gshard(x2d: jax.Array, p: dict, cfg: MoEConfig):
    """Capacity-bounded top-k MoE FFN as dispatch/combine einsums
    (GShard/Switch formulation). x2d [T, D] → ([T, D], aux_loss). With
    ``we_*`` sharded over an ``ep`` axis, XLA lowers the ``tec``-contractions
    to all-to-alls over the expert axis — the differentiable twin of the
    Pallas dispatch/combine path below."""
    T, D = x2d.shape
    E, k = cfg.num_experts, cfg.topk
    C = max(int(cfg.capacity_factor * T * k / E), 1)
    C = min(C, T)

    logits = (x2d.astype(jnp.float32) @ p["w_router"])          # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_ids = lax.top_k(probs, k)                   # [T, k]
    gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)

    # position of each (token, k) in its expert's capacity buffer
    e_oh = jax.nn.one_hot(gate_ids, E, dtype=jnp.int32)         # [T, k, E]
    flat = e_oh.reshape(T * k, E)
    pos_flat = jnp.cumsum(flat, axis=0) - flat                  # exclusive
    pos = jnp.take_along_axis(
        pos_flat.reshape(T, k, E), gate_ids[..., None], -1)[..., 0]  # [T, k]
    keep = pos < C
    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, C), C, dtype=x2d.dtype)
    disp = jnp.einsum("tke,tkc->tec", e_oh.astype(x2d.dtype), pos_oh)
    comb = jnp.einsum("tke,tkc,tk->tec", e_oh.astype(jnp.float32),
                      pos_oh.astype(jnp.float32),
                      gate_vals * keep.astype(jnp.float32))

    xe = jnp.einsum("td,tec->ecd", x2d, disp)                   # [E, C, D]
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, p["we_gate"],
                               preferred_element_type=jnp.float32)
                    ).astype(x2d.dtype) \
        * jnp.einsum("ecd,edf->ecf", xe, p["we_up"])
    ye = jnp.einsum("ecf,efd->ecd", h, p["we_down"])            # [E, C, D]
    y = jnp.einsum("ecd,tec->td", ye.astype(jnp.float32), comb)

    # Switch-style load-balance aux loss
    me = jnp.mean(probs, axis=0)                                 # [E]
    ce = jnp.mean(e_oh[:, 0].astype(jnp.float32), axis=0)        # top-1 frac
    aux = cfg.router_aux_coef * E * jnp.sum(me * ce)
    return y.astype(x2d.dtype), aux


def moe_block_apply(cfg: MoEConfig, x: jax.Array, p: dict,
                    positions: jax.Array, act_spec: P | None = None):
    """One MoE transformer block → (x, aux_loss). x [B, S, D]."""
    import math as _math
    b = cfg.base
    B, S, D = x.shape
    Hq, Hkv, Dh = b.n_heads, b.n_kv_heads, b.head_dim

    def pin(h):
        if act_spec is not None:
            h = lax.with_sharding_constraint(h, act_spec)
        return h

    h = rmsnorm(x, p["attn_norm"], b.norm_eps)
    q = rope((h @ p["wq"]).reshape(B, S, Hq, Dh), positions, b.rope_theta)
    kk = rope((h @ p["wk"]).reshape(B, S, Hkv, Dh), positions, b.rope_theta)
    v = (h @ p["wv"]).reshape(B, S, Hkv, Dh)
    attn = _attention(q, kk, v, 1.0 / _math.sqrt(Dh))
    x = pin(x + attn.reshape(B, S, Hq * Dh) @ p["wo"])

    h = rmsnorm(x, p["mlp_norm"], b.norm_eps)
    y, aux = moe_mlp_gshard(h.reshape(B * S, D), p, cfg)
    x = pin(x + y.reshape(B, S, D))
    return x, aux


def moe_forward(params: dict, tokens: jax.Array, cfg: MoEConfig,
                act_spec: P | None = None, remat: bool = False):
    """Full MoE forward → (logits [B,S,V] f32, aux_loss scalar)."""
    b = cfg.base
    B, S = tokens.shape
    x = params["embed"][tokens].astype(b.dtype)
    positions = jnp.arange(S)[None, :].repeat(B, 0)

    def body(carry, p):
        x, aux = carry
        x, a = moe_block_apply(cfg, x, p, positions, act_spec)
        return (x, aux + a), None

    if remat:
        body = jax.checkpoint(body)
    (x, aux), _ = lax.scan(body, (x, jnp.float32(0)), params["blocks"])
    x = rmsnorm(x, params["final_norm"], b.norm_eps)
    return (x @ params["lm_head"]).astype(jnp.float32), aux


# ---------------------------------------------------------------------------
# inference path: Pallas EP overlap kernels
# ---------------------------------------------------------------------------

def moe_mlp_ep_overlap(ctx: ShmemContext, a2a_layer, x2d: jax.Array,
                       router_w: jax.Array, we_gate: jax.Array,
                       we_up: jax.Array, we_down: jax.Array,
                       axis: str | None = None, block_m: int = 128,
                       block_n: int = 128, block_k: int | None = None,
                       down_block_n: int | None = None,
                       we_gate_up_packed: jax.Array | None = None,
                       microbatches: int = 1
                       ) -> jax.Array:
    """The reference's EP MoE inference block (test_ep_moe_inference.py /
    tutorial 04) on the Pallas kernel stack: router → low-latency A2A
    dispatch → grouped expert FFN on each rank's local experts → A2A combine
    with top-k weights.

    x2d [T, D] globally P(axis)-sharded token rows; router_w [D, E];
    we_* [E, D, F]/[E, F, D] — each rank uses its local expert slice
    we_*[me*Elocal:(me+1)*Elocal].

    With a 2-tier layer (``EPAll2AllLayer.create(axis=(major, minor))``)
    the dispatch/combine run the hierarchical path and ``axis`` is taken
    from the layer; ``x2d`` is P((major, minor))-sharded.

    ``microbatches=M > 1`` runs ISSUE 16's double-buffered schedule: the
    router still scores the FULL batch (identical math), then the per-rank
    token rows are split into M contiguous row blocks, each dispatched
    through an M-times-smaller (still drop-proof) a2a context, with block
    i+1's dispatch issued BEFORE block i's expert FFN — the grouped FFN on
    microbatch i overlaps the a2a of microbatch i+1 (gated per-segment by
    the counted-signal wire when the layer sets ``seg_push``). The output
    is the FIXED-ORDER per-rank concatenation of the block outputs; since
    every per-row quantity (routing decision, gather, quant round-trip,
    expert FFN row, fixed k-order combine fold) is bitwise invariant to
    which rows share its batch, the result is BITWISE identical to
    ``microbatches=1`` — the schedule overlaps, the reduction order never
    moves.
    """
    from triton_dist_tpu.ops.all_to_all import QuantTokens
    from triton_dist_tpu.ops.group_gemm import (PackedGatedWeights,
                                                apply_grouped, grouped_gemm,
                                                grouped_gemm_gated)
    from triton_dist_tpu.shmem import device as shd

    a2a = a2a_layer.a2a
    is_2d = getattr(a2a_layer, "is_2d", False)
    if is_2d:
        group = a2a.axes
        shard_spec = P(group)
    else:
        group = axis or a2a.axis or ctx.axis_names[0]
        shard_spec = P(group)
    E, k = a2a.num_experts, a2a.topk
    e_local = a2a.experts_per_rank

    if isinstance(we_gate_up_packed, PackedGatedWeights):
        # layer-level contract check of the serving weight layout: the
        # interleave is invisible in the array's shape, so mismatches are
        # only catchable while the pack width still rides the type
        assert we_gate_up_packed.block_n == block_n, (
            f"we_gate_up_packed was packed with "
            f"block_n={we_gate_up_packed.block_n} but the layer runs "
            f"block_n={block_n} — repack with pack_gated_weights(..., "
            f"block_n={block_n})")
        we_gate_up_packed = we_gate_up_packed.w

    # expert-major recv layout (1d contexts): rows [e*cap_e, (e+1)*cap_e) of
    # every src block belong to local expert e by construction, so the
    # block→expert table is a static constant and the align gather/scatter
    # passes are skipped entirely (the roofline attributed ~25 % extra
    # weight traffic to their ragged block padding)
    expert_major = (not is_2d) and getattr(a2a, "expert_major", False)
    cap_e = a2a.capacity_per_expert if expert_major else None
    em_fast = expert_major and cap_e % block_m == 0

    logits = x2d.astype(jnp.float32) @ router_w
    gate_vals, gate_ids = lax.top_k(jax.nn.softmax(logits, -1), k)
    gate_vals = (gate_vals / jnp.sum(gate_vals, -1, keepdims=True))

    mbs = int(microbatches)
    if mbs > 1:
        import dataclasses as _dc
        from triton_dist_tpu.ops.all_to_all import _cap_round
        assert not is_2d, "microbatched overlap is a 1d-EP schedule"
        assert not expert_major, (
            "microbatched overlap needs the rank-major layout: the per-"
            "expert budget of an expert-major context is not drop-proof "
            "per microbatch, so drops could differ from the unsplit path")
        T = a2a.max_tokens
        assert T % mbs == 0, (
            f"per-rank rows {T} not divisible by microbatches={mbs}")
        itemsize = jnp.dtype(a2a.wire_dtype or a2a.dtype).itemsize
        assert a2a.capacity >= _cap_round(T * k, itemsize), (
            "microbatched overlap requires a drop-proof capacity "
            f"(>= {T}*{k} rounded) — a tuned sub-worst-case capacity "
            "drops per-microbatch routing spill differently from the "
            "unsplit dispatch and breaks bit-identity")
        mbT = T // mbs
        # the microbatch context: same wire dtype / edges / seg_push, an
        # M-times-smaller (still drop-proof) slot budget. Reusing the FULL
        # layer's resolved wire_dtype is what keeps a "auto" wire decision
        # independent of M (it was resolved at the full dispatch size).
        mb_a2a = _dc.replace(a2a, max_tokens=mbT,
                             capacity=_cap_round(mbT * k, itemsize))
        mb_layer = _dc.replace(a2a_layer, a2a=mb_a2a)

        def _mb_part(i):
            def f(x, gv, gi):
                s = lambda a: lax.dynamic_slice_in_dim(a, i * mbT, mbT, 0)
                return s(x), s(gv), s(gi)
            return ctx.shard_map(f, in_specs=(shard_spec,) * 3,
                                 out_specs=(shard_spec,) * 3)(
                x2d, gate_vals, gate_ids)

        parts = [_mb_part(i) for i in range(mbs)]
    else:
        mb_layer = a2a_layer
        parts = [(x2d, gate_vals, gate_ids)]

    # software pipeline prologue: microbatch 0's a2a is in flight before
    # any expert FFN is traced (at mbs == 1 this is exactly the original
    # dispatch call)
    disp = [mb_layer.dispatch(parts[0][0], parts[0][2])]
    quant = isinstance(disp[0][0], QuantTokens)

    n = ctx.axis_size(group)

    packed = we_gate_up_packed is not None

    def expert_ffn(tok, ids, wg, wu, wd, *sc):
        me = shd.my_pe(group)
        H = tok.shape[-1]
        rows = 1
        for d in tok.shape[:-1]:
            rows *= d
        tflat = tok.reshape(rows, H)
        iflat = ids.reshape(rows)
        sflat = sc[0].reshape(rows) if sc else None
        # packed serving layout: wg carries the pre-interleaved [E, H, 2F]
        # gate‖up weights (pack_gated_weights — one double-width tile
        # stream, measured 538.9→381.5 µs for the gate+up kernel at the
        # deployed full-K (128,128) config; wu unused)
        wg_l = lax.dynamic_slice_in_dim(wg, me * e_local, e_local)
        wu_l = (None if packed
                else lax.dynamic_slice_in_dim(wu, me * e_local, e_local))
        wd_l = lax.dynamic_slice_in_dim(wd, me * e_local, e_local)
        if packed:
            # re-carry the pack width on the per-rank slice so the kernel
            # re-validates it (the layer-level check above ran on the full
            # table; the slice is a fresh bare array)
            wg_l = PackedGatedWeights(wg_l, block_n)

        # gated FFN: silu(x@wg) * (x@wu) @ wd over local experts, as TWO
        # fused kernels: gate+up+act in one (each x-tile read once,
        # activation on the f32 accumulators in VMEM — no gate/up arrays
        # or elementwise pass in HBM), then the down grouped GEMM. On the
        # expert-edge quantized wire, xs stays fp8/int8 and the per-row
        # scale folds into both accumulators — silu(s·(q@wg)) · s·(q@wu)
        # == the dequantized math, row scaling commutes with the matmul.
        # masked=False: apply_grouped's scatter drops invalid rows by
        # index, so the zeroing pass over each output is skipped.
        def ffn(xs, be, nb, *ss):
            kw = dict(block_m=block_m, block_n=block_n, n_blocks_used=nb,
                      masked=False, block_k=block_k, packed=packed)
            if ss:
                kw["row_scale"] = ss[0]
                kw["out_dtype"] = a2a.dtype
            hh = grouped_gemm_gated(xs, wg_l, wu_l, be, **kw)
            # down default bn=512: measured best on-chip at the DeepSeek
            # serving shape (432.7 µs at bn=128 -> 199.8 at bn=512 — the
            # (F, 128) weight tiles were DMA-overhead-bound; 1024/1792
            # overshoot: 336/357 µs; scripts/moe_probe.py round 5)
            return grouped_gemm(hh, wd_l, be, block_m=block_m,
                                block_n=down_block_n or 512,
                                n_blocks_used=nb, masked=False)

        # fp8 wire rows are cast to the compute dtype inside the gather
        # pass (Mosaic rejects fp8 x-strips in the grouped pipelines on
        # the current toolchain — measured round 5; int8 rows feed the
        # kernels directly and use the convert-once scratch). The scale
        # keeps riding the accumulators either way.
        gdt = (a2a.dtype if (quant and jnp.issubdtype(tflat.dtype,
                                                      jnp.floating))
               else None)
        if em_fast:
            # expert-major fast path: the recv buffer IS expert-aligned.
            # Block b sits at row offset (b·bm) mod cap of its src block,
            # whose expert segment is that offset // cap_e — a static
            # constant (cap_e % block_m == 0 means no block straddles a
            # segment). No align gather, no inverse scatter: the slots are
            # already the combine order, and unfilled slots are zero rows
            # whose FFN output is zero (scale 1 on the quantized wire).
            # ALL row blocks run (the per-expert budget makes that the
            # roofline count — vs the ragged-padding blocks the align
            # pass added on the rank-major layout).
            cap = a2a.capacity
            be = jnp.asarray([(b * block_m % cap) // cap_e
                              for b in range(rows // block_m)], jnp.int32)
            xs = tflat if gdt is None else tflat.astype(gdt)
            out = (ffn(xs, be, rows // block_m, sflat)
                   if sflat is not None else ffn(xs, be, rows // block_m))
        else:
            out = apply_grouped(tflat, iflat, e_local, ffn, block_m=block_m,
                                row_scale=sflat, gather_dtype=gdt)
        if is_2d:
            return out.reshape(tok.shape[:-1] + (-1,))
        return out.reshape(n, tok.shape[-2], -1)

    w_spec = P(None, None, None)
    sm = ctx.shard_map(expert_ffn,
                       in_specs=(shard_spec,) * 2 + (w_spec,) * 3
                       + (shard_spec,) * (1 if quant else 0),
                       out_specs=shard_spec)
    # packed mode: the interleaved weights ride the wg slot; wu is passed
    # as a zero-size placeholder the ffn never touches
    wgu = we_gate_up_packed if packed else we_gate
    wup = (jnp.zeros((a2a.num_experts, 1, 1), we_gate.dtype) if packed
           else we_up)

    outs = []
    for i in range(len(parts)):
        if i + 1 < len(parts):
            # issue microbatch i+1's dispatch BEFORE microbatch i's FFN:
            # the grouped GEMMs below overlap the next block's wire time
            disp.append(mb_layer.dispatch(parts[i + 1][0], parts[i + 1][2]))
        recv_tok, recv_ids, layout = disp[i]
        args = ((recv_tok.q, recv_ids, wgu, wup, we_down, recv_tok.scale)
                if quant else (recv_tok, recv_ids, wgu, wup, we_down))
        processed = sm(*args)
        outs.append(mb_layer.combine(processed, layout, parts[i][1]))
    if len(outs) == 1:
        return outs[0]
    # fixed-order per-rank concatenation restores the original row order —
    # a concat, never a reduction, so the bitwise contract holds
    return ctx.shard_map(lambda *os: jnp.concatenate(os, axis=0),
                         in_specs=(shard_spec,) * len(outs),
                         out_specs=shard_spec)(*outs)


def moe_mlp_tp_overlap(ctx: ShmemContext, x2d: jax.Array,
                       router_w: jax.Array, we_up: jax.Array,
                       we_down: jax.Array, topk: int,
                       axis: str | None = None,
                       block_m: int = 128) -> jax.Array:
    """The reference's MoE-TP inference block on the FUSED overlap kernels
    (test_ag_moe + test_moe_reduce_rs composed, the
    "AG+GroupGEMM → GroupGEMM+topk-reduce+RS" pipeline of
    allgather_group_gemm.py + moe_reduce_rs.py):

    1. router → top-k experts per token,
    2. ``ag_moe_group_gemm``: tokens allgathered across the TP group while
       the grouped up-projection streams arrived segments (weights
       column-sharded [E, D, F] P(None, None, axis)),
    3. activation,
    4. ``moe_reduce_rs``: grouped down-projection on the F-shard
       (weights row-sharded [E, F, D] P(None, axis, None)) ring-scattered
       to token owners with the topk-weighted fold at the end.

    x2d [T, D] sharded P(axis) on T; returns [T, D] sharded P(axis).
    Every (token, k) pair is one row through both grouped GEMMs — the
    reference's row expansion (moe_reduce_rs.py select_experts)."""
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm, moe_reduce_rs

    axis = axis or ctx.axis_names[0]
    D = x2d.shape[1]
    k = topk

    logits = x2d.astype(jnp.float32) @ router_w
    gate_vals, gate_ids = lax.top_k(jax.nn.softmax(logits, -1), k)
    gate_vals = gate_vals / jnp.sum(gate_vals, -1, keepdims=True)

    # one row per (token, k) pair, keeping rows of one token adjacent
    def expand(x_shard, ids_shard):
        rep = jnp.repeat(x_shard[:, None, :], k, axis=1).reshape(-1, D)
        return rep, ids_shard.reshape(-1)

    rep, ids_flat = ctx.shard_map(
        expand, in_specs=(P(axis), P(axis)),
        out_specs=(P(axis), P(axis)))(x2d, gate_ids)

    # up-projection overlapped with the token allgather; output
    # [T*k, F] sharded P(None, axis)
    h = ag_moe_group_gemm(ctx, rep, ids_flat, we_up, axis=axis,
                          block_m=block_m)
    h = jax.nn.silu(h.astype(jnp.float32)).astype(x2d.dtype)

    # moe_reduce_rs needs the replicated global row→expert map; the fused
    # kernel path gathered it internally already, here once more for the
    # second stage (control-plane-sized: T*k ints)
    ids_rep = ctx.shard_map(
        lambda i: lax.all_gather(i, axis, tiled=True),
        in_specs=P(axis), out_specs=P(None))(ids_flat)

    return moe_reduce_rs(ctx, h, ids_rep, gate_vals, we_down, axis=axis,
                         block_m=block_m)


def moe_decode_step_sp(ctx: ShmemContext, a2a_layer, params: dict,
                       token: jax.Array, pos: jax.Array, cfg: MoEConfig,
                       cache: dict, sp_axis: str | None = None,
                       ag_method: str = "fused"
                       ) -> tuple[jax.Array, dict]:
    """DeepSeek-style serving decode step — BOTH showcase paths in one
    jitted step: sequence-parallel distributed flash-decode attention over
    the KV cache sharded on ``sp_axis`` (reference
    sp_flash_decode_layer.py:78-184) and the expert-parallel MoE FFN
    through the low-latency A2A dispatch/combine (test_ep_moe_inference.py
    composition). The single-axis deployment uses ONE axis for both: KV
    sequence shards and expert shards live on the same devices, which is
    the reference's serving topology (SP decode ranks == EP ranks).

    ``token`` [B] int32 with B = n_ranks * a2a.max_tokens;
    ``pos`` scalar int32; ``cache`` as ``init_kv_cache(cfg.base, ...)``
    stacked per layer, k/v sharded P(None, None, None, sp_axis, None).
    Returns (logits [B, V] f32, updated cache).

    Thin composition over ``llama.decode_step_sp``'s ``ffn`` hook — the
    attention/cache plumbing lives in exactly one place."""
    from triton_dist_tpu.models.llama import decode_step_sp

    a2a = a2a_layer.a2a
    assert a2a.num_experts == cfg.num_experts, (
        f"a2a layer built for {a2a.num_experts} experts but cfg routes "
        f"over {cfg.num_experts} — gate ids would address nonexistent "
        "ranks/slots")
    assert a2a.topk == cfg.topk, (a2a.topk, cfg.topk)

    def moe_ffn(h, p):
        return moe_mlp_ep_overlap(ctx, a2a_layer, h, p["w_router"],
                                  p["we_gate"], p["we_up"], p["we_down"])

    return decode_step_sp(ctx, params, token, pos, cfg.base, cache,
                          axis=sp_axis, ag_method=ag_method, ffn=moe_ffn)


__all__ = ["MoEConfig", "init_moe_params", "moe_param_specs",
           "moe_mlp_gshard", "moe_block_apply", "moe_forward",
           "moe_mlp_ep_overlap", "moe_mlp_tp_overlap", "moe_decode_step_sp"]
