"""Checkpoint/restore for the serving engines: snapshot + journal replay.

A checkpoint is a JSON-able snapshot of the *control plane only* — scheduler
queues, `KVPagePool` ledger, request cursors, terminal sets.  No KV bytes are
ever persisted: the trace-determinism contract (greedy argmax decode, LIFO
page allocation, strict-FIFO scheduling) guarantees that a request restarted
from its prompt regenerates bit-identical tokens, so `restore()` simply
requeues every live request at cursor 0 and lets the already-compiled chunk
program re-prefill it.  Restore therefore compiles **zero** new programs:
it touches host state only and reuses the engine's existing jitted
decode/chunk executables.

Restore pipeline::

    checkpoint (state @ step S, journal high-water mark Q)
        │ engine._restore_state(state)     rebuild scheduler/ledger/terminals;
        │                                  live requests requeued at cursor 0
        ▼
    journal suffix (seq > Q)               replayed in order:
        submit  -> engine.submit(...)      re-enqueue post-snapshot arrivals
        finish  -> tokens from the entry   settle post-snapshot completions
        reject/expire/fail -> terminals    re-settle typed terminals
        requeue -> drop from queue         the request moved to a peer replica
                                           during an elastic drain (ISSUE 18)
        ▼
    engine._steps = max(S, last entry step); decode resumes

The checkpoint's page-ledger snapshot is *not* used to re-own pages (pages
are re-earned by re-prefill); it is used as an integrity audit — the ledger
is rebuilt from the snapshot and its FNV-1a digest compared against the
digest recorded at capture time, catching torn or tampered snapshots.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.kv_pool import KVPagePool
from triton_dist_tpu.serving.scheduler import Request, RequestState


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint's recorded ledger digest does not match its snapshot."""


@dataclasses.dataclass(frozen=True)
class Checkpoint:
    """One engine snapshot.  ``journal_seq`` is the newest journal entry the
    snapshot already covers; restore replays only entries after it."""

    step: int
    digest: int
    journal_seq: int
    state: dict[str, Any]


# ----------------------------------------------------------- request (de)ser
def snapshot_request(req: Request) -> dict[str, Any]:
    """JSON-able view of a live request.  Generated tokens and the prefill
    cursor are recorded for observability but deliberately *not* restored —
    restart-from-prompt regenerates them bit-identically."""
    return {
        "rid": req.rid,
        "prompt": list(req.prompt),
        "max_new_tokens": req.max_new_tokens,
        "eos_token": req.eos_token,
        "generated": list(req.generated),
        "cursor": req.prefill_cursor,
        "preemptions": req.preemptions,
        "admitted_seq": req.admitted_seq,
        "submit_step": req.submit_step,
        "retries": req.retries,
        "degradations": req.degradations,
        # multi-tenant stamps (ISSUE 14): absent in pre-v2 snapshots —
        # rebuild_request fills the defaults, so old checkpoints restore
        "tenant": req.tenant,
        "cls": req.cls,
        "shed_level": req.shed_level,
    }


def rebuild_request(snap: dict[str, Any]) -> Request:
    """Rebuild a snapshot as a fresh QUEUED request at cursor 0 — the
    restart-from-prompt form that deterministic replay makes bit-identical."""
    req = Request(
        rid=snap["rid"],
        prompt=tuple(snap["prompt"]),
        max_new_tokens=snap["max_new_tokens"],
        eos_token=snap.get("eos_token"),
    )
    req.state = RequestState.QUEUED
    req.preemptions = snap.get("preemptions", 0)
    req.submit_step = snap.get("submit_step", 0)
    req.retries = snap.get("retries", 0)
    req.degradations = snap.get("degradations", 0)
    req.tenant = snap.get("tenant", "default")
    req.cls = snap.get("cls", "default")
    req.shed_level = snap.get("shed_level", 0)
    return req


def snapshot_finished(req: Request) -> dict[str, Any]:
    """JSON-able terminal record of a finished request: the tokens plus
    the latency/preemption numbers the original process measured (restored
    verbatim — a settled terminal is never re-measured)."""
    return {
        "rid": req.rid,
        "prompt": list(req.prompt),
        "tokens": list(req.generated),
        "submit_step": req.submit_step,
        "first_token_step": req.first_token_step,
        "preemptions": req.preemptions,
    }


def audit_pool_snapshot(snap: dict[str, Any], digest: int, num_pages: int,
                        page_size: int, reserved: int) -> None:
    """Rebuild a ledger from its snapshot and check the recorded digest."""
    pool = KVPagePool.from_snapshot(snap, num_pages, page_size, reserved=reserved)
    got = pool.digest()
    if got != (digest & 0xFFFFFFFF):
        raise CheckpointIntegrityError(
            f"page-ledger snapshot digest 0x{got:08x} != recorded "
            f"0x{digest & 0xFFFFFFFF:08x} — checkpoint is torn or tampered")


def audit_prefix_snapshot(entries: list, digest: int) -> None:
    """Check a prefix-index snapshot (ISSUE 13) against its recorded
    digest. Like the pool audit, the index is never restored — a rebuilt
    engine re-earns KV via re-prefill and starts with an empty cache —
    but a torn/tampered snapshot must still fail loudly."""
    from triton_dist_tpu.serving.prefix_cache import PrefixCache
    got = PrefixCache.snapshot_digest(entries)
    if got != (digest & 0xFFFFFFFF):
        raise CheckpointIntegrityError(
            f"prefix-index snapshot digest 0x{got:08x} != recorded "
            f"0x{digest & 0xFFFFFFFF:08x} — checkpoint is torn or tampered")


# ------------------------------------------------------------------ capture
def capture(engine: Any) -> Checkpoint:
    """Snapshot an engine's control plane.  Pure host work, no dispatches."""
    journal = engine.journal
    seq = journal.last_seq if journal is not None else -1
    return Checkpoint(step=engine._steps, digest=engine.control_digest(),
                      journal_seq=seq, state=engine._capture_state())


def latest(journal: ControlJournal | None) -> Checkpoint | None:
    """Newest checkpoint recorded in the journal, or None."""
    if journal is None:
        return None
    e = journal.last_checkpoint_entry()
    if e is None:
        return None
    return Checkpoint(step=e["step"], digest=e["digest"],
                      journal_seq=e["journal_seq"], state=e["state"])


# ------------------------------------------------------------------ restore
def restore(engine: Any, ckpt: Checkpoint | None,
            journal: ControlJournal | None) -> dict[str, Any]:
    """Rebuild ``engine``'s control plane from ``ckpt`` (may be None — then
    the whole journal is the suffix) and replay the journal suffix.

    Works both in place (the crashed process recovering itself, e.g. the
    sharded digest-divergence rung) and on a freshly constructed engine of
    the same configuration (process restart).  Either way no new programs
    are compiled: restore performs zero device dispatches and the engine's
    existing jitted executables are reused when decode resumes.
    """
    t0 = time.perf_counter()
    engine._journal_muted = True   # replay must not re-journal its own events
    engine._replaying = True       # replayed submits bypass the admission cap
    replayed = 0
    last_step = ckpt.step if ckpt is not None else 0
    try:
        engine._restore_state(ckpt.state if ckpt is not None else None)
        suffix = journal.suffix(ckpt.journal_seq if ckpt is not None else -1) \
            if journal is not None else []
        for e in suffix:
            last_step = max(last_step, e["step"])
            kind = e["kind"]
            if kind == "submit":
                engine.submit(tuple(e["prompt"]), e["max_new_tokens"],
                              rid=e["rid"], tenant=e.get("tenant"),
                              cls=e.get("cls"))
                # re-stamp the ORIGINAL submit step (reporting only —
                # replay-time submit() stamped the checkpoint step)
                sched = getattr(engine, "sched_p", None) or engine.sched
                if sched.queue and sched.queue[-1].rid == e["rid"]:
                    sched.queue[-1].submit_step = e["step"]
                replayed += 1
            elif kind == "finish":
                engine._restore_finished(e["rid"], list(e["tokens"]), meta=e)
                replayed += 1
            elif kind in ("reject", "expire", "fail"):
                engine._restore_terminal(e["rid"], kind, e.get("reason", ""),
                                         e.get("error_type"))
                replayed += 1
            elif kind == "requeue":
                # elastic drain (ISSUE 18): the request moved to a peer
                # replica AFTER its submit was journaled here — drop it
                # from the replayed queue or the restored engine would
                # serve a request the cluster already re-placed
                engine._pop_queued(e["rid"])
                replayed += 1
            # admit/chunk/grow/preempt/handoff/migrate/checkpoint/restore/
            # digest_divergence entries carry no state restore needs: slot
            # seating and page ownership are re-earned by deterministic
            # re-admission + re-prefill.
        engine._steps = max(engine._steps, last_step)
    finally:
        engine._journal_muted = False
        engine._replaying = False
    engine._incarnation += 1
    engine.metrics.inc("restores")
    engine.metrics.observe("restore_s", time.perf_counter() - t0)
    engine._jlog("restore", replayed=replayed,
                 from_step=ckpt.step if ckpt is not None else None)
    return {"replayed": replayed, "resume_step": engine._steps,
            "checkpoint_step": ckpt.step if ckpt is not None else None}
