"""n>1 Mosaic-lowering gate: AOT-compile every overlap kernel against an
abstract 8-device v5e TPU topology — no silicon required.

Interpret-mode tests (the rest of tests/) validate protocol semantics but
not Mosaic lowering; the real chip here is a single device, so kernels can
hit n>1-only lowering bugs that nothing catches before a pod run (the class
``dispatch_2d`` was suspected of in round 2). jax's compile-only topology
client (``jax.experimental.topologies`` over the local libtpu) closes the
gap: ``jit(fn).lower(shaped_args).compile()`` runs the full XLA+Mosaic
pipeline for a v5e-8 mesh and fails loudly on lowering bugs.

Parity: the reference's AOT kernel list compile coverage
(scripts/aot_kernels.txt via tools/compile_aot.py, SURVEY §5.9) — there the
AOT build compiles every shipped kernel signature ahead of time; here the
same sweep doubles as the multi-chip lowering gate.

Bisection note (round 3): ``dispatch_2d``/``combine_2d``/fp8 compile clean
here at (2,4) AND at a (1,1) mesh with the local libtpu — the round-2
on-chip hang is therefore NOT a client-side Mosaic compile bug; suspicion
moves to the remote-compile server path / execution (see verify skill notes).
"""

import os

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from conftest import REPO_ROOT  # noqa: F401  (conftest forces the CPU mesh)
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.shmem.context import ShmemContext

N8 = 8


@pytest.fixture(scope="module", autouse=True)
def _force_compiled_env():
    """Force the compiled Mosaic path (the ops would otherwise pick
    interpret mode off the CPU default backend) and quiet libtpu's host
    introspection; persistent compile cache amortizes reruns."""
    saved = {k: os.environ.get(k) for k in
             ("TDT_FORCE_COMPILED", "TPU_ACCELERATOR_TYPE",
              "TPU_WORKER_HOSTNAMES", "TPU_SKIP_MDS_QUERY")}
    os.environ["TDT_FORCE_COMPILED"] = "1"
    os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5litepod-8")
    os.environ.setdefault("TPU_WORKER_HOSTNAMES", "localhost")
    # Off-GCE there is no metadata server; libtpu's probe retries for
    # ~7 minutes before giving up (measured 433s of fixture setup).
    # Everything the MDS would provide is already pinned above.
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")
    saved_cache_dir = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", "/tmp/tdt_topo_cache")
    yield
    jax.config.update("jax_compilation_cache_dir", saved_cache_dir)
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


@pytest.fixture(scope="module")
def topo():
    from jax.experimental import topologies
    try:
        return topologies.get_topology_desc("v5e:2x4", "tpu")
    except Exception as e:
        # a process killed mid-libtpu-init leaves a stale lockfile that
        # would otherwise silently SKIP the whole n>1 lowering gate; only
        # remove it if no live process holds the lock (non-blocking flock)
        if "libtpu_lockfile" in str(e) and _remove_stale_libtpu_lock():
            try:
                return topologies.get_topology_desc("v5e:2x4", "tpu")
            except Exception as e2:  # pragma: no cover
                pytest.skip(f"local libtpu topology unavailable: {e2}")
        pytest.skip(f"local libtpu topology unavailable: {e}")


def _remove_stale_libtpu_lock(path: str = "/tmp/libtpu_lockfile") -> bool:
    import errno
    import fcntl
    try:
        fd = os.open(path, os.O_RDWR)
    except OSError:
        return False
    try:
        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError as err:
        os.close(fd)
        if err.errno in (errno.EACCES, errno.EAGAIN):
            return False  # a live process holds it — do not yank
        return False
    os.close(fd)
    try:
        os.remove(path)
    except OSError:
        return False
    return True


@pytest.fixture(scope="module")
def ctx1d(topo):
    from jax.experimental import topologies
    return ShmemContext(mesh=topologies.make_mesh(topo, (N8,), ("x",)))


@pytest.fixture(scope="module")
def ctx2d(topo):
    from jax.experimental import topologies
    return ShmemContext(mesh=topologies.make_mesh(topo, (2, 4), ("o", "i")))


def sds(ctx, shape, spec, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype,
                                sharding=NamedSharding(ctx.mesh, spec))


def compile_ok(fn, *args):
    exe = jax.jit(fn).lower(*args).compile()
    assert exe is not None


# -- collectives -------------------------------------------------------------

@pytest.mark.parametrize("method", ["push", "ring"])
def test_all_gather_lowers_8dev(ctx1d, method):
    from triton_dist_tpu.ops import all_gather
    x = sds(ctx1d, (N8 * 8, 128), P("x"))
    compile_ok(lambda v: all_gather(ctx1d, v, axis="x", method=method), x)


def test_push2d_all_gather_lowers_8dev(ctx2d):
    from triton_dist_tpu.ops import all_gather
    x = sds(ctx2d, (N8 * 8, 128), P(("o", "i")))
    compile_ok(lambda v: all_gather(ctx2d, v, method="push_2d"), x)


def test_reduce_scatter_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops import reduce_scatter
    x = sds(ctx1d, (N8 * 8, 128), P("x"))
    compile_ok(lambda v: reduce_scatter(ctx1d, v, axis="x"), x)


# -- overlap ops -------------------------------------------------------------

def test_ag_gemm_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    M = K = 512
    N = 128 * N8
    a = sds(ctx1d, (M, K), P("x"))
    b = sds(ctx1d, (K, N), P(None, "x"))
    compile_ok(lambda u, v: ag_gemm(ctx1d, u, v, axis="x",
                                    cfg=GemmConfig(M // N8, 128)), a, b)


def test_ag_gemm_2tier_lowers_8dev(ctx2d):
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    axes = ("o", "i")
    M, K, N = 512, 128, N8 * 128
    a = sds(ctx2d, (M, K), P(axes))
    b = sds(ctx2d, (K, N), P(None, axes))
    compile_ok(lambda u, v: ag_gemm(ctx2d, u, v, axis=axes,
                                    cfg=GemmConfig(M // N8, 128)), a, b)


def test_gemm_rs_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs
    M, K, N = N8 * 32, N8 * 128, 128
    a = sds(ctx1d, (M, K), P(None, "x"))
    b = sds(ctx1d, (K, N), P("x", None))
    compile_ok(lambda u, v: gemm_rs(ctx1d, u, v, axis="x",
                                    cfg=GemmConfig(32, 128)), a, b)


def test_gemm_rs_2tier_lowers_8dev(ctx2d):
    from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs
    axes = ("o", "i")
    M, K, N = N8 * 32, N8 * 128, 128
    a = sds(ctx2d, (M, K), P(None, axes))
    b = sds(ctx2d, (K, N), P(axes, None))
    compile_ok(lambda u, v: gemm_rs(ctx2d, u, v, axis=axes,
                                    cfg=GemmConfig(32, 128)), a, b)


def test_reduce_scatter_multitier_lowers_8dev(ctx2d):
    from triton_dist_tpu.ops import reduce_scatter
    x = sds(ctx2d, (N8 * N8 * 2, 128), P(("o", "i")))
    compile_ok(lambda v: reduce_scatter(ctx2d, v, method="ring_2d"), x)


# -- EP all-to-all -----------------------------------------------------------

def test_a2a_dispatch_combine_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops.all_to_all import (combine,
                                                create_all_to_all_context,
                                                dispatch)
    T, H, topk = N8 * 4, 128, 2
    a2a = create_all_to_all_context(ctx1d, max_tokens=T // N8, hidden=H,
                                    topk=topk, num_experts=2 * N8, axis="x")
    t = sds(ctx1d, (T, H), P("x"), jnp.bfloat16)
    i = sds(ctx1d, (T, topk), P("x"), jnp.int32)
    w = sds(ctx1d, (T, topk), P("x"))

    def roundtrip(tt, ii, ww):
        recv, _, layout = dispatch(a2a, tt, ii)
        return combine(a2a, recv, layout, ww)

    compile_ok(roundtrip, t, i, w)


def test_a2a_fused_dequant_lowers_8dev(ctx1d):
    """capacity=128 → the IN-KERNEL per-arrival dequant (emit_pipeline with
    the lane→sublane scale broadcast) must lower at n=8."""
    from triton_dist_tpu.ops.all_to_all import (combine,
                                                create_all_to_all_context,
                                                dispatch)
    T, H, topk = N8 * 4, 128, 2
    a2a = create_all_to_all_context(ctx1d, max_tokens=T // N8, hidden=H,
                                    topk=topk, num_experts=2 * N8, axis="x",
                                    capacity=128,
                                    wire_dtype=jnp.float8_e4m3fn)
    assert a2a.capacity == 128
    t = sds(ctx1d, (T, H), P("x"), jnp.bfloat16)
    i = sds(ctx1d, (T, topk), P("x"), jnp.int32)
    w = sds(ctx1d, (T, topk), P("x"))

    def roundtrip(tt, ii, ww):
        recv, _, layout = dispatch(a2a, tt, ii)
        return combine(a2a, recv, layout, ww)

    compile_ok(roundtrip, t, i, w)


@pytest.mark.parametrize("wire", [None, jnp.float8_e4m3fn])
def test_a2a_2tier_lowers_8dev(ctx2d, wire):
    """The round-2 on-chip hang suspect: 2-tier dispatch+combine, bf16 and
    quantized wire."""
    from triton_dist_tpu.ops.all_to_all import (combine_2d,
                                                create_all_to_all_context_2d,
                                                dispatch_2d)
    T, H, topk, E = 8, 128, 2, 16
    a2a = create_all_to_all_context_2d(ctx2d, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=E,
                                       dtype=jnp.bfloat16, wire_dtype=wire)
    spec = P(("o", "i"))
    t = sds(ctx2d, (N8 * T, H), spec, jnp.bfloat16)
    i = sds(ctx2d, (N8 * T, topk), spec, jnp.int32)
    w = sds(ctx2d, (N8 * T, topk), spec)

    def roundtrip(tt, ii, ww):
        recv, _, layouts = dispatch_2d(a2a, tt, ii)
        return combine_2d(a2a, recv, layouts, ww)

    compile_ok(roundtrip, t, i, w)


def test_a2a_2tier_dcn_outer_lowers_8dev(ctx2d, monkeypatch):
    """2-slice virtual topology (VERDICT r4 #6): the OUTER tier forced
    onto DCN compiles the XLA all_to_all variant while the inner tier
    keeps the Pallas kernel — the real multi-slice deployment shape."""
    from triton_dist_tpu.ops.all_to_all import (combine_2d,
                                                create_all_to_all_context_2d,
                                                dispatch_2d)
    monkeypatch.setenv("TDT_DCN_AXES", "o")
    T, H, topk, E = 8, 128, 2, 16
    a2a = create_all_to_all_context_2d(ctx2d, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=E,
                                       dtype=jnp.bfloat16)
    spec = P(("o", "i"))
    t = sds(ctx2d, (N8 * T, H), spec, jnp.bfloat16)
    i = sds(ctx2d, (N8 * T, topk), spec, jnp.int32)
    w = sds(ctx2d, (N8 * T, topk), spec)

    def roundtrip(tt, ii, ww):
        recv, _, layouts = dispatch_2d(a2a, tt, ii)
        return combine_2d(a2a, recv, layouts, ww)

    compile_ok(roundtrip, t, i, w)


def test_ag_gemm_2tier_dcn_outer_lowers_8dev(ctx2d, monkeypatch):
    """2-tier AG-GEMM with the outer tier on DCN: XLA gather outer +
    Pallas overlap inner compiles on the abstract topology."""
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    monkeypatch.setenv("TDT_DCN_AXES", "o")
    axes = ("o", "i")
    M, K, N = 512, 128, N8 * 128
    a = sds(ctx2d, (M, K), P(axes))
    b = sds(ctx2d, (K, N), P(None, axes))
    compile_ok(lambda u, v: ag_gemm(ctx2d, u, v, axis=axes,
                                    cfg=GemmConfig(M // N8, 128)), a, b)


def test_moe_2tier_lowers_8dev(ctx2d):
    """Hierarchical MoE overlap ops (AG+GroupGEMM and GroupGEMM+RS over an
    axis tuple) — the inter-node analog paths."""
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm, moe_reduce_rs
    axes = ("o", "i")
    E, H, N, T = 4, 128, N8 * 128, N8 * 32
    t = sds(ctx2d, (T, H), P(axes))
    i = sds(ctx2d, (T,), P(axes), jnp.int32)
    w = sds(ctx2d, (E, H, N), P(None, None, axes))
    compile_ok(lambda tt, ii, ww: ag_moe_group_gemm(ctx2d, tt, ii, ww,
                                                    axis=axes, block_m=32),
               t, i, w)

    K, N2, Tr, topk = N8 * 128, 128, N8 * 8, 2
    t2 = sds(ctx2d, (Tr * topk, K), P(None, axes))
    i2 = sds(ctx2d, (Tr * topk,), P(), jnp.int32)
    tw = sds(ctx2d, (Tr, topk), P())
    w2 = sds(ctx2d, (E, K, N2), P(None, axes, None))
    compile_ok(lambda a, b, c, d: moe_reduce_rs(ctx2d, a, b, c, d,
                                                axis=axes, block_m=16),
               t2, i2, tw, w2)


def test_ring_attention_dp_composed_lowers_8dev(ctx2d):
    """Ring attention with an independent ring per dp row (batch_axis
    composition) on a (2, 4) mesh."""
    from triton_dist_tpu.ops.ring_attention import ring_attention
    B, H, D, s_loc = 2, 2, 128, 128
    S = 4 * s_loc
    spec = P("o", None, "i")
    q = sds(ctx2d, (B, H, S, D), spec)
    k = sds(ctx2d, (B, H, S, D), spec)
    v = sds(ctx2d, (B, H, S, D), spec)
    compile_ok(lambda a, b, c: ring_attention(ctx2d, a, b, c, axis="i",
                                              batch_axis="o", causal=True,
                                              block_q=128, block_k=128),
               q, k, v)


# -- three-tier hierarchy ----------------------------------------------------

@pytest.fixture(scope="module")
def ctx3d(topo):
    from jax.experimental import topologies
    return ShmemContext(mesh=topologies.make_mesh(topo, (2, 2, 2),
                                                  ("a", "b", "c")))


def test_three_tier_lowers_8dev(ctx3d):
    """3-axis hierarchical AG + AG-GEMM (reference push_3d family parity,
    low_latency_allgather.py:345-530) must lower at (2,2,2)."""
    from triton_dist_tpu.ops import all_gather
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    axes = ("a", "b", "c")
    x = sds(ctx3d, (N8 * 8, 128), P(axes))
    compile_ok(lambda v: all_gather(ctx3d, v, method="push_2d"), x)
    M, K, N = 512, 128, N8 * 128
    a = sds(ctx3d, (M, K), P(axes))
    b = sds(ctx3d, (K, N), P(None, axes))
    compile_ok(lambda u, v: ag_gemm(ctx3d, u, v, axis=axes,
                                    cfg=GemmConfig(M // N8, 128)), a, b)


# -- MoE overlap -------------------------------------------------------------

def test_ag_moe_group_gemm_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm
    E, H, N, T = 4, 128, N8 * 128, N8 * 32
    t = sds(ctx1d, (T, H), P("x"))
    i = sds(ctx1d, (T,), P("x"), jnp.int32)
    w = sds(ctx1d, (E, H, N), P(None, None, "x"))
    compile_ok(lambda tt, ii, ww: ag_moe_group_gemm(ctx1d, tt, ii, ww,
                                                    block_m=32), t, i, w)


def test_moe_reduce_rs_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops.moe import moe_reduce_rs
    E, K, N, T, topk = 4, N8 * 128, 128, N8 * 8, 2
    t = sds(ctx1d, (T * topk, K), P(None, "x"))
    i = sds(ctx1d, (T * topk,), P(), jnp.int32)
    tw = sds(ctx1d, (T, topk), P())
    w = sds(ctx1d, (E, K, N), P(None, "x", None))
    compile_ok(lambda tt, ii, tww, ww: moe_reduce_rs(ctx1d, tt, ii, tww, ww,
                                                     block_m=16),
               t, i, tw, w)


# -- ring attention (training CP) --------------------------------------------

def _qkv_sds(ctx, n, B=1, Hq=2, Hkv=2, s_loc=128, D=128):
    spec = P(None, None, "x")
    S = n * s_loc
    return (sds(ctx, (B, Hq, S, D), spec), sds(ctx, (B, Hkv, S, D), spec),
            sds(ctx, (B, Hkv, S, D), spec))


def test_ring_attention_fwd_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops.ring_attention import ring_attention
    q, k, v = _qkv_sds(ctx1d, N8)
    compile_ok(lambda a, b, c: ring_attention(ctx1d, a, b, c, axis="x",
                                              causal=True, block_q=128,
                                              block_k=128), q, k, v)


def test_ring_attention_bwd_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops.ring_attention import ring_attention
    q, k, v = _qkv_sds(ctx1d, N8)

    def loss(a, b, c):
        return ring_attention(ctx1d, a, b, c, axis="x", causal=True,
                              block_q=128, block_k=128).astype(
            jnp.float32).sum()

    compile_ok(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


def test_ring_attention_unaligned_tiles_raise(ctx1d):
    """The compiled-backend tile guard must fire with a clear error for
    shapes whose derived tiles are lane-unaligned — BEFORE Mosaic's opaque
    memref_slice rejection, and through every public entry."""
    from triton_dist_tpu.ops.ring_attention import (ring_attention,
                                                    ring_attention_bwd,
                                                    ring_attention_fwd)
    # zigzag chunks of 64 rows (s_loc=128)
    q, k, v = _qkv_sds(ctx1d, N8, s_loc=128)
    for entry in (ring_attention, ring_attention_fwd):
        with pytest.raises(ValueError, match="128-multiple"):
            jax.jit(lambda a, b, c, e=entry: e(
                ctx1d, a, b, c, axis="x", layout="zigzag")).lower(q, k, v)
    with pytest.raises(ValueError, match="128-multiple"):
        o = sds(ctx1d, q.shape, P(None, None, "x"))
        lse = sds(ctx1d, q.shape[:2] + (q.shape[2],), P(None, None, "x"))
        jax.jit(lambda a, b, c, oo, ll, dd: ring_attention_bwd(
            ctx1d, a, b, c, oo, ll, dd, axis="x", causal=True,
            sm_scale=None, layout="zigzag")).lower(q, k, v, o, lse, q)
    # contiguous with a sub-128 derived tile (block_q=64)
    with pytest.raises(ValueError, match="128-multiple"):
        jax.jit(lambda a, b, c: ring_attention(
            ctx1d, a, b, c, axis="x", block_q=64)).lower(q, k, v)


def test_ring_attention_zigzag_bwd_lowers_8dev(ctx1d):
    """The load-balanced causal layout (fwd+bwd) — its two-chunk tile
    offsets exercise different slicing than contiguous. s_loc=256 so each
    zigzag chunk is 128 rows (the compiled-backend floor the op enforces;
    s_loc=128 → 64-row chunks is rejected with a clear error)."""
    from triton_dist_tpu.ops.ring_attention import ring_attention
    q, k, v = _qkv_sds(ctx1d, N8, s_loc=256)

    def loss(a, b, c):
        return ring_attention(ctx1d, a, b, c, axis="x", causal=True,
                              block_q=128, block_k=128,
                              layout="zigzag").astype(jnp.float32).sum()

    compile_ok(jax.grad(loss, argnums=(0, 1, 2)), q, k, v)


# -- full serving composition ------------------------------------------------

def test_moe_decode_step_lowers_8dev(ctx1d):
    """The DeepSeek-style serving step (SP flash-decode attention + EP A2A
    MoE FFN, models.moe.moe_decode_step_sp) — the widest single graph in
    the framework — must lower at n=8 in one piece."""
    from triton_dist_tpu.layers.ep_a2a_layer import EPAll2AllLayer
    from triton_dist_tpu.models.llama import LlamaConfig
    from triton_dist_tpu.models.moe import (MoEConfig, init_moe_params,
                                            moe_decode_step_sp)
    base = LlamaConfig(vocab_size=256, d_model=1024, n_layers=2, n_heads=8,
                       n_kv_heads=2, d_ff=256, max_seq_len=N8 * 128)
    cfg = MoEConfig(base=base, num_experts=2 * N8, topk=2, moe_d_ff=128)
    B, S, L = N8, base.max_seq_len, base.n_layers
    layer = EPAll2AllLayer.create(ctx1d, max_tokens=B // N8,
                                  hidden=base.d_model, topk=cfg.topk,
                                  num_experts=cfg.num_experts, axis="x",
                                  dtype=base.dtype)
    params = jax.eval_shape(lambda k: init_moe_params(k, cfg),
                            jax.random.key(0))  # shapes only, no init work
    params = jax.tree.map(
        lambda s: sds(ctx1d, s.shape, P(), s.dtype), params)
    Hkv, D = base.n_kv_heads, base.head_dim
    kv = sds(ctx1d, (L, B, Hkv, S, D), P(None, None, None, "x", None),
             base.dtype)
    cache = {"k": kv, "v": kv}
    token = sds(ctx1d, (B,), P(), jnp.int32)
    pos = sds(ctx1d, (), P(), jnp.int32)

    compile_ok(lambda p, t, po, c: moe_decode_step_sp(
        ctx1d, layer, p, t, po, cfg, c, sp_axis="x"), params, token, pos,
        cache)


# -- distributed decode ------------------------------------------------------

def test_fused_sp_decode_lowers_8dev(ctx1d):
    from triton_dist_tpu.ops.flash_decode import sp_gqa_flash_decode
    B, Hq, Hkv, D, s_local = 1, 4, 2, 128, 128
    S = N8 * s_local
    q = sds(ctx1d, (B, Hq, D), P())
    k = sds(ctx1d, (B, Hkv, S, D), P(None, None, "x"))
    v = sds(ctx1d, (B, Hkv, S, D), P(None, None, "x"))
    kv = sds(ctx1d, (B,), P(), jnp.int32)
    compile_ok(lambda *a: sp_gqa_flash_decode(ctx1d, *a, ag_method="fused"),
               q, k, v, kv)


@pytest.fixture(scope="module")
def ctx_single(topo):
    """1-device mesh carved from the same topology: the n=1 causal
    contiguous path (flat valid-tile walk over SMEM tile maps) only
    activates at axis size 1."""
    from jax.experimental import topologies
    mesh1 = jax.sharding.Mesh(topologies.make_mesh(
        topo, (N8,), ("x",)).devices[:1], ("x",))
    return ShmemContext(mesh=mesh1)


def test_ring_attention_flat_walk_lowers_1dev(ctx_single):
    """n=1 causal flat walk: Mosaic must accept the SMEM tile-map inputs
    and the dynamic qi_ref[t]/kvi_ref[t] index maps in the 1-D pipeline
    (interpret mode does not model either constraint)."""
    from triton_dist_tpu.ops.ring_attention import ring_attention
    B, Hq, Hkv, S, D = 1, 4, 2, 1024, 128
    q = sds(ctx_single, (B, Hq, S, D), P(None, None, "x"), jnp.bfloat16)
    kv = sds(ctx_single, (B, Hkv, S, D), P(None, None, "x"), jnp.bfloat16)
    compile_ok(lambda a, b, c: ring_attention(
        ctx_single, a, b, c, axis="x", causal=True,
        block_q=256, block_k=256), q, kv, kv)
